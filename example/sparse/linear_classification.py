"""Sparse linear classification on LibSVM data — BASELINE config 5
(ref: example/sparse/linear_classification/train.py: CSR data through
LibSVMIter, a RowSparse weight updated store-side, row_sparse_pull
fetching only the rows a batch touches).

Data: a real .libsvm file via ``--data``; otherwise a synthetic sparse
two-class problem is generated on the fly (no egress here).  Model:
logistic regression over a high-dimensional sparse feature space —
``scores = X_csr · w + b`` via ``mx.nd.sparse.dot``; the weight gradient
is row-sparse (only features present in the batch), pushed to the kvstore
whose server-side SGD applies it (update_on_kvstore, the reference's
sparse flow), and the next batch row_sparse_pulls just the rows it needs.

Usage:
    python linear_classification.py
    python linear_classification.py --data path/to/train.libsvm --dim 47236
    python ../../tools/launch.py -n 2 python linear_classification.py \
        --kv-store dist_sync
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, autograd  # noqa: E402


def make_synthetic_libsvm(path, n=2000, dim=1000, nnz=12, seed=0):
    """Two-class sparse data: label = sign(w_true · x)."""
    rs = np.random.RandomState(seed)
    w_true = rs.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            idx = np.sort(rs.choice(dim, size=nnz, replace=False))
            val = rs.randn(nnz)
            label = 1 if float(w_true[idx] @ val) > 0 else 0
            feats = " ".join("%d:%.5f" % (i, v) for i, v in zip(idx, val))
            f.write("%d %s\n" % (label, feats))
    return path


def main():
    parser = argparse.ArgumentParser(description="sparse linear classifier")
    parser.add_argument("--data", default="", help=".libsvm file (synthetic "
                        "fallback when empty)")
    parser.add_argument("--dim", type=int, default=1000,
                        help="feature dimension")
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--num-epochs", type=int, default=12)
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--seed", type=int, default=7)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    if "dist" in args.kv_store:
        from incubator_mxnet_tpu.parallel import dist
        dist.init_process()
    mx.random.seed(args.seed)

    # per-rank file: concurrent workers must not race a shared tmp path
    synth = "/tmp/sparse_example_rank%d.libsvm" % (
        int(os.environ.get("MX_PROCESS_ID", "0")))
    path = args.data or make_synthetic_libsvm(synth, dim=args.dim)
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(args.dim,),
                          batch_size=args.batch_size)

    kv = mx.kv.create(args.kv_store)
    rank, nw = kv.rank, max(kv.num_workers, 1)

    w = nd.zeros((args.dim, 1)).tostype("row_sparse")
    b = nd.zeros((1,))
    kv.init("w", w)
    kv.init("b", b)
    # server-side optimizer: pushes apply the update ON the store and
    # pulls return weights (the reference's update_on_kvstore sparse flow)
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=args.lr))

    # dist kvstore pushes are lockstep collectives: every rank must issue
    # the SAME number — truncate to a batch count divisible by num_workers
    with open(path) as f:
        n_rows = sum(1 for line in f if line.strip())
    n_batches = math.ceil(n_rows / args.batch_size)
    common = (n_batches // nw) * nw if nw > 1 else n_batches

    final_acc = 0.0
    for epoch in range(args.num_epochs):
        it.reset()
        total = correct = 0
        loss_sum = 0.0
        nbatches = 0
        for bi, batch in enumerate(it):
            if bi >= common:
                break       # keep collective counts rank-identical
            if nw > 1 and bi % nw != rank:
                continue    # shard batches across workers
            x_csr = batch.data[0]          # CSRNDArray
            y = batch.label[0]
            # pull ONLY the rows this batch touches (row_sparse_pull —
            # the PS-era embedding/linear-model fast path)
            row_ids = nd.array(np.unique(np.asarray(
                x_csr.indices.asnumpy(), dtype=np.int64)))
            kv.row_sparse_pull("w", out=w, row_ids=row_ids)
            kv.pull("b", out=b)
            dense_w = w.tostype("default")
            dense_w.attach_grad()
            b.attach_grad()
            with autograd.record():
                scores = nd.sparse.dot(x_csr, dense_w) + b
                z = scores.reshape((-1,))
                loss = nd.mean(nd.log(1 + nd.exp(-(2 * y - 1) * z)))
            loss.backward()
            # only rows present in the batch carry gradient: row-sparse push
            kv.push("w", dense_w.grad.tostype("row_sparse"))
            kv.push("b", b.grad)
            loss_sum += float(loss.asscalar())
            nbatches += 1
            pred = (np.asarray(z.asnumpy()) > 0).astype(np.int64)
            correct += int((pred == y.asnumpy().astype(np.int64)).sum())
            total += len(pred)
        final_acc = correct / max(total, 1)
        logging.info("epoch %d loss %.4f acc %.3f", epoch,
                     loss_sum / max(nbatches, 1), final_acc)
    print("final training accuracy: %.4f" % final_acc)


if __name__ == "__main__":
    main()
