"""Gluon LSTM word language model — BASELINE config 3
(ref: example/gluon/word_language_model/train.py: imperative Gluon blocks,
hybridize(), truncated-BPTT batching).

Data: a character-level corpus synthesized from a small Markov chain (no
egress here) — structured enough that a trained model beats the unigram
entropy by a wide margin; point ``--data`` at any UTF-8 text file for the
real thing.  Model: embedding → multi-layer LSTM (lax.scan fused kernel)
→ tied-dimension projection, trained with truncated BPTT windows.

Usage:
    python word_lm.py
    python word_lm.py --data corpus.txt --num-epochs 5
    python word_lm.py --fused          # one-jit DataParallelTrainer path
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, autograd, gluon  # noqa: E402
from incubator_mxnet_tpu.gluon import nn, rnn  # noqa: E402


def synth_corpus(n=20000, seed=0):
    """Markov-chain characters over a 26-symbol alphabet."""
    rs = np.random.RandomState(seed)
    V = 26
    trans = rs.dirichlet(np.ones(V) * 0.2, size=V)
    out = np.zeros(n, np.int64)
    s = 0
    for i in range(n):
        s = rs.choice(V, p=trans[s])
        out[i] = s
    return out, V


def load_corpus(path):
    with open(path, "rb") as f:
        raw = f.read()
    uniq, ids = np.unique(np.frombuffer(raw, np.uint8), return_inverse=True)
    return ids.astype(np.int64), len(uniq)


class WordLM(gluon.HybridBlock):
    def __init__(self, vocab, embed, hidden, layers):
        super().__init__()
        with self.name_scope():
            self.embed = nn.Embedding(vocab, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=layers, layout="NTC",
                                 input_size=embed)
            self.proj = nn.Dense(vocab, flatten=False, in_units=hidden)

    def hybrid_forward(self, F, x):
        return self.proj(self.lstm(self.embed(x)))


def main():
    parser = argparse.ArgumentParser(description="gluon word LM")
    parser.add_argument("--data", default="", help="text file (synthetic "
                        "Markov corpus when empty)")
    parser.add_argument("--embed", type=int, default=64)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--bptt", type=int, default=32)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-epochs", type=int, default=6)
    parser.add_argument("--lr", type=float, default=0.003)
    parser.add_argument("--fused", action="store_true",
                        help="train via the one-jit DataParallelTrainer")
    parser.add_argument("--seed", type=int, default=1)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    corpus, vocab = (load_corpus(args.data) if args.data
                     else synth_corpus())
    # truncated-BPTT batching: (num_windows, batch, bptt)
    per_row = len(corpus) // args.batch_size
    trimmed = corpus[:per_row * args.batch_size].reshape(
        args.batch_size, per_row)
    nwin = (per_row - 1) // args.bptt
    xs = np.stack([trimmed[:, i * args.bptt:(i + 1) * args.bptt]
                   for i in range(nwin)])
    ys = np.stack([trimmed[:, i * args.bptt + 1:(i + 1) * args.bptt + 1]
                   for i in range(nwin)])

    net = WordLM(vocab, args.embed, args.hidden, args.layers)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    if args.fused:
        from incubator_mxnet_tpu.parallel import DataParallelTrainer
        trainer = DataParallelTrainer(
            net, loss_fn, optimizer="adam",
            optimizer_params={"learning_rate": args.lr})
        step = lambda x, y: float(np.asarray(trainer.step(
            mx.nd.array(x.astype(np.float32)),
            mx.nd.array(y.astype(np.float32)))))
    else:
        net.hybridize()
        gtr = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
        carry = {"s": None}   # hidden state rides across BPTT windows,
        # detached each step — the reference word LM's defining pattern

        def step(x, y):
            xb = nd.array(x.astype(np.float32))
            yb = nd.array(y.astype(np.float32))
            if carry["s"] is None:
                carry["s"] = net.lstm.begin_state(x.shape[0])
            with autograd.record():
                h = net.embed(xb)
                out, new_s = net.lstm(h, carry["s"])
                loss = loss_fn(net.proj(out), yb)
            loss.backward()
            gtr.step(x.shape[0])
            carry["s"] = [st.detach() for st in new_s]
            return float(loss.asnumpy().mean())

    for epoch in range(args.num_epochs):
        tot = 0.0
        if not args.fused:
            carry["s"] = None   # each epoch restarts the sequence
        for i in range(nwin):
            tot += step(xs[i], ys[i])
        ppl = math.exp(min(tot / nwin, 20))
        logging.info("epoch %d loss %.4f ppl %.2f", epoch, tot / nwin, ppl)
    # unigram entropy is the "model learned nothing" bar
    counts = np.bincount(corpus, minlength=vocab).astype(np.float64)
    p = counts / counts.sum()
    unigram_ppl = math.exp(-(p[p > 0] * np.log(p[p > 0])).sum())
    logging.info("unigram ppl %.2f", unigram_ppl)
    print("final ppl: %.4f (unigram %.2f)" % (ppl, unigram_ppl))


if __name__ == "__main__":
    main()
