"""Single-shot object detection — BASELINE config 4 (ref: example/ssd:
the multibox CUDA ops, here TPU formulations in ops/vision.py).

A compact SSD: small conv backbone → per-location class scores + box
offsets over MultiBoxPrior anchors; training targets from MultiBoxTarget
(anchor matching + hard-negative mining semantics), loss = softmax CE on
classes + smooth-L1 on masked offsets; inference decodes + NMS via
MultiBoxDetection.  Data: synthetic scenes — one colored square per image
on textured background — generated on the fly (no egress here); plug a
real ImageDetIter via --data-rec for .rec detection datasets
(im2rec-packed, label [cls x1 y1 x2 y2] normalized).

Usage:
    python train.py                     # synthetic, CPU-mesh friendly
    python train.py --num-epochs 20 --eval-iou 0.5
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd, autograd, gluon  # noqa: E402
from incubator_mxnet_tpu.gluon import nn  # noqa: E402


NUM_CLASSES = 2          # background + square
SIZES = (0.3, 0.55)
RATIOS = (1.0, 2.0, 0.5)
NUM_ANCHORS = len(SIZES) + len(RATIOS) - 1


def make_scene(rs, size=32):
    """One image with one axis-aligned bright square; returns (img CHW,
    label (1, 5) [cls, x1, y1, x2, y2] normalized)."""
    img = rs.rand(3, size, size).astype(np.float32) * 0.3
    s = rs.randint(size // 4, size // 2)
    x0 = rs.randint(0, size - s)
    y0 = rs.randint(0, size - s)
    img[:, y0:y0 + s, x0:x0 + s] = rs.rand(3, 1, 1) * 0.5 + 0.5
    # class ids are 0-based in labels; MultiBoxTarget emits id+1 with 0 =
    # background (multibox_target.cc convention)
    label = np.array([[0, x0 / size, y0 / size,
                       (x0 + s) / size, (y0 + s) / size]], np.float32)
    return img, label


class TinySSD(gluon.HybridBlock):
    """Backbone + twin heads (ref: example/ssd/symbol — one scale here)."""

    def __init__(self):
        super().__init__()
        with self.name_scope():
            self.backbone = nn.HybridSequential()
            for filters in (16, 32, 64):
                self.backbone.add(nn.Conv2D(filters, 3, padding=1,
                                            strides=2),
                                  nn.BatchNorm(),
                                  nn.Activation("relu"))
            self.cls_head = nn.Conv2D(NUM_ANCHORS * NUM_CLASSES, 3,
                                      padding=1)
            self.loc_head = nn.Conv2D(NUM_ANCHORS * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        feat = self.backbone(x)
        cls = self.cls_head(feat)    # (B, A*C, H, W)
        loc = self.loc_head(feat)    # (B, A*4, H, W)
        B = x.shape[0]
        cls = F.reshape(F.transpose(cls, axes=(0, 2, 3, 1)),
                        shape=(B, -1, NUM_CLASSES))     # (B, HWA, C)
        loc = F.reshape(F.transpose(loc, axes=(0, 2, 3, 1)),
                        shape=(B, -1))                  # (B, HWA*4)
        return feat, cls, loc


def smooth_l1(x):
    ax = nd.abs(x)
    return nd.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


def main():
    parser = argparse.ArgumentParser(description="tiny SSD")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-epochs", type=int, default=12)
    parser.add_argument("--num-batches", type=int, default=4)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--eval-iou", type=float, default=0.3)
    parser.add_argument("--seed", type=int, default=3)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rs = np.random.RandomState(args.seed)

    n = args.batch_size * args.num_batches
    imgs, labels = zip(*(make_scene(rs) for _ in range(n)))
    X = np.stack(imgs)
    Y = np.stack(labels)

    net = TinySSD()
    net.initialize(mx.init.Xavier(magnitude=2.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    anchors = None
    for epoch in range(args.num_epochs):
        tot = 0.0
        for i in range(0, n, args.batch_size):
            xb = nd.array(X[i:i + args.batch_size])
            yb = nd.array(Y[i:i + args.batch_size])
            with autograd.record():
                feat, cls, loc = net(xb)
                if anchors is None:
                    anchors = nd.MultiBoxPrior(feat, sizes=SIZES,
                                               ratios=RATIOS)
                loc_t, loc_m, cls_t = nd.MultiBoxTarget(
                    anchors, yb, nd.transpose(cls, axes=(0, 2, 1)),
                    negative_mining_ratio=3.0)
                # anchors marked ignore_label (-1) by hard-negative mining
                # must not reach the CE (the reference feeds SoftmaxOutput
                # with use_ignore=True); mask them out explicitly
                valid = cls_t >= 0
                oh = nd.one_hot(nd.broadcast_maximum(cls_t, nd.zeros((1,))),
                                depth=NUM_CLASSES)
                ce = -nd.sum(oh * nd.log_softmax(cls, axis=-1), axis=-1)
                nvalid = nd.broadcast_maximum(nd.sum(valid, axis=1),
                                              nd.ones((1,)))
                l_cls = nd.sum(ce * valid, axis=1) / nvalid
                l_loc = nd.mean(smooth_l1((loc - loc_t) * loc_m),
                                axis=1)
                loss = l_cls + l_loc
            loss.backward()
            trainer.step(xb.shape[0])
            tot += float(nd.mean(loss).asscalar())
        logging.info("epoch %d loss %.4f", epoch,
                     tot / max(args.num_batches, 1))

    # -- evaluation: decode + NMS, IoU of top detection vs ground truth --
    hits = 0
    for i in range(n):
        xb = nd.array(X[i:i + 1])
        feat, cls, loc = net(xb)
        probs = nd.softmax(cls, axis=-1)
        dets = nd.MultiBoxDetection(
            nd.transpose(probs, axes=(0, 2, 1)), loc, anchors,
            nms_threshold=0.45)
        d = dets.asnumpy()[0]
        d = d[d[:, 0] >= 0]
        if not len(d):
            continue
        best = d[np.argmax(d[:, 1])]
        gt = Y[i, 0, 1:]
        bx = best[2:6]
        ix1, iy1 = max(bx[0], gt[0]), max(bx[1], gt[1])
        ix2, iy2 = min(bx[2], gt[2]), min(bx[3], gt[3])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        union = ((bx[2] - bx[0]) * (bx[3] - bx[1])
                 + (gt[2] - gt[0]) * (gt[3] - gt[1]) - inter)
        if union > 0 and inter / union >= args.eval_iou:
            hits += 1
    recall = hits / n
    logging.info("detection recall@IoU%.1f = %.3f", args.eval_iou, recall)
    print("recall: %.4f" % recall)


if __name__ == "__main__":
    main()
