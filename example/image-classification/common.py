"""Shared fit plumbing for the image-classification examples.

Plays the role of the reference's example/image-classification/common/fit.py
(argument surface, kvstore wiring, lr schedule), rebuilt for this
framework's surfaces: Module.fit, the Gluon Trainer loop, and the fused
DataParallelTrainer.
"""
import argparse
import logging
import time

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon


def add_fit_args(parser):
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--optimizer", default="sgd")
    parser.add_argument("--kv-store", default="local",
                        help="local | device | dist_sync")
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--seed", type=int, default=42)
    return parser


def fit_module(symbol, train_iter, val_iter, args):
    """Train through the Module API (ref: base_module.py fit)."""
    kv = mx.kv.create(args.kv_store)
    mod = mx.mod.Module(symbol, context=mx.context.Context.default_ctx())
    batch_end = mx.callback.Speedometer(args.batch_size, args.disp_batches)
    mod.fit(train_iter,
            eval_data=val_iter,
            num_epoch=args.num_epochs,
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr,
                              "momentum": args.momentum, "wd": args.wd},
            initializer=mx.init.Xavier(magnitude=2.0),
            batch_end_callback=batch_end,
            eval_metric="acc")
    score = mod.score(val_iter, "acc")
    for name, val in score:
        logging.info("final validation %s=%f", name, val)
    return dict(score)["accuracy"]


def fit_gluon(net, train_iter, val_iter, args):
    """Train the same workload through Gluon blocks + Trainer
    (ref: gluon/trainer.py semantics)."""
    kv = mx.kv.create(args.kv_store)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    trainer = gluon.Trainer(net.collect_params(), args.optimizer,
                            {"learning_rate": args.lr,
                             "momentum": args.momentum, "wd": args.wd},
                            kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    from incubator_mxnet_tpu import autograd
    for epoch in range(args.num_epochs):
        train_iter.reset()
        tic = time.time()
        n = 0
        for i, batch in enumerate(train_iter):
            x, y = batch.data[0], batch.label[0]
            if x.dtype == np.uint8:   # raw-record pipeline: cast on use
                x = x.astype("float32")
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0] * max(kv.num_workers, 1))
            n += x.shape[0]
            if args.disp_batches and (i + 1) % args.disp_batches == 0:
                logging.info("epoch %d batch %d speed %.1f samples/s",
                             epoch, i + 1, n / (time.time() - tic))
        logging.info("epoch %d done in %.1fs", epoch, time.time() - tic)
    return evaluate_gluon(net, val_iter)


def evaluate_gluon(net, val_iter):
    val_iter.reset()
    correct = total = 0
    for batch in val_iter:
        x = batch.data[0]
        if x.dtype == np.uint8:
            x = x.astype("float32")
        out = net(x).asnumpy()
        y = batch.label[0].asnumpy()
        keep = len(y) - batch.pad
        correct += (out.argmax(1)[:keep] == y[:keep]).sum()
        total += keep
    acc = correct / max(total, 1)
    logging.info("final validation accuracy=%f", acc)
    return acc


def fit_fused(net, train_iter, val_iter, args, dtype="bfloat16"):
    """Train through the fused one-jit DataParallelTrainer — the TPU-first
    fast path the bench uses (forward+loss+backward+update as ONE XLA
    program, batch sharded over the mesh "dp" axis)."""
    from incubator_mxnet_tpu.parallel import DataParallelTrainer
    net.initialize(mx.init.Xavier(magnitude=2.0))
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr,
                          "momentum": args.momentum, "wd": args.wd},
        dtype=None if dtype in (None, "float32") else dtype)
    for epoch in range(args.num_epochs):
        train_iter.reset()
        tic = time.time()
        n = 0
        loss = None
        for i, batch in enumerate(train_iter):
            loss = trainer.step(batch.data[0], batch.label[0])
            n += batch.data[0].shape[0]
            if args.disp_batches and (i + 1) % args.disp_batches == 0:
                logging.info("epoch %d batch %d speed %.1f samples/s",
                             epoch, i + 1, n / (time.time() - tic))
        logging.info("epoch %d done in %.1fs (last loss %.4f)",
                     epoch, time.time() - tic,
                     float(np.asarray(loss)) if loss is not None else -1)
    trainer.sync_params()
    return evaluate_gluon(net, val_iter)
