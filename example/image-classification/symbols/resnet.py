"""Symbolic ResNet family for the Module / quantization pipelines.

Spec-driven builder (ref: example/image-classification/symbols/resnet.py
— the reference's hand-unrolled per-depth functions become one plan
table, the same style as the repo's Gluon zoo): post-activation v1
residual units (conv-BN-relu), the variant whose conv+BN pairs fold
cleanly for INT8 serving (contrib.quantization.fold_batchnorm).
"""
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402

# depth -> (bottleneck?, units per stage); stage filters fixed per family
SPECS = {
    18: (False, (2, 2, 2, 2)),
    34: (False, (3, 4, 6, 3)),
    50: (True, (3, 4, 6, 3)),
    101: (True, (3, 4, 23, 3)),
    152: (True, (3, 8, 36, 3)),
}


def _conv_bn(data, num_filter, kernel, stride, pad, name, act=True):
    c = mx.sym.Convolution(data, kernel=kernel, stride=stride, pad=pad,
                           num_filter=num_filter, no_bias=True,
                           name=name + "_conv")
    b = mx.sym.BatchNorm(c, fix_gamma=False, eps=2e-5, momentum=0.9,
                         name=name + "_bn")
    return mx.sym.Activation(b, act_type="relu", name=name + "_relu") \
        if act else b


def residual_unit(data, num_filter, stride, dim_match, name, bottle_neck):
    if bottle_neck:
        mid = num_filter // 4
        plan = [(mid, (1, 1), (1, 1), (0, 0)),
                (mid, (3, 3), stride, (1, 1)),
                (num_filter, (1, 1), (1, 1), (0, 0))]
    else:
        plan = [(num_filter, (3, 3), stride, (1, 1)),
                (num_filter, (3, 3), (1, 1), (1, 1))]
    x = data
    for i, (f, k, st, pad) in enumerate(plan):
        # the LAST conv-bn of the unit has no relu: activation follows
        # the shortcut add (post-activation v1)
        x = _conv_bn(x, f, k, st, pad, "%s_c%d" % (name, i + 1),
                     act=(i + 1 < len(plan)))
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", act=False)
    return mx.sym.Activation(x + shortcut, act_type="relu",
                             name=name + "_out")


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224",
               thumbnail=False, **kwargs):
    """ResNet-v1 Symbol ending in SoftmaxOutput (drop it via
    ``sym.get_internals()`` or take ``softmax`` off for serving)."""
    bottle_neck, units = SPECS[num_layers]
    filters = (256, 512, 1024, 2048) if bottle_neck else (64, 128, 256, 512)

    data = mx.sym.var("data")
    if thumbnail:
        x = _conv_bn(data, 64, (3, 3), (1, 1), (1, 1), "stem")
    else:
        x = _conv_bn(data, 64, (7, 7), (2, 2), (3, 3), "stem")
        x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", name="stem_pool")
    for s, (n_units, f) in enumerate(zip(units, filters)):
        for u in range(n_units):
            stride = (1, 1) if (s == 0 or u > 0) else (2, 2)
            x = residual_unit(x, f, stride, dim_match=(u > 0),
                              name="stage%d_unit%d" % (s + 1, u + 1),
                              bottle_neck=bottle_neck)
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(7, 7),
                       name="pool_final")
    x = mx.sym.Flatten(x, name="flat")
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
