"""Train an MLP or LeNet on MNIST — the reference's canonical first
example (example/image-classification/train_mnist.py), rebuilt on this
framework's surfaces.

Data: real MNIST idx files when ``--data-dir`` points at them
(train-images-idx3-ubyte[.gz] etc.); otherwise a deterministic synthetic
stand-in with learnable class structure (this environment has no network
egress), same shapes, same iterator API.

Surfaces: default = Module.fit on the declarative Symbol graph;
``--gluon`` = imperative Gluon blocks + Trainer.  Both support
``--kv-store dist_sync`` under tools/launch.py for multi-process runs.

Usage:
    python train_mnist.py                     # Module, synthetic MNIST
    python train_mnist.py --gluon --network lenet
    python tools/launch.py -n 2 python train_mnist.py --kv-store dist_sync
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
# make the in-repo package importable when run straight from a checkout
sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))
import common  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon  # noqa: E402


def load_mnist(data_dir, n_synth=4096):
    """(train_x, train_y, val_x, val_y) — idx files or synthetic."""
    import gzip
    import struct

    def read_idx(lbl, img):
        def _open(p):
            return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")
        with _open(lbl) as f:
            struct.unpack(">II", f.read(8))
            y = np.frombuffer(f.read(), dtype=np.uint8)
        with _open(img) as f:
            struct.unpack(">IIII", f.read(16))
            x = np.frombuffer(f.read(), dtype=np.uint8)
        x = x.reshape(len(y), 1, 28, 28).astype(np.float32) / 255.0
        return x, y.astype(np.float32)

    if data_dir:
        def find(stem):
            for suf in ("", ".gz"):
                p = os.path.join(data_dir, stem + suf)
                if os.path.exists(p):
                    return p
            raise FileNotFoundError(stem)
        tx, ty = read_idx(find("train-labels-idx1-ubyte"),
                          find("train-images-idx3-ubyte"))
        vx, vy = read_idx(find("t10k-labels-idx1-ubyte"),
                          find("t10k-images-idx3-ubyte"))
        return tx, ty, vx, vy

    # synthetic: 10 class templates + noise — learnable, zero downloads
    rs = np.random.RandomState(7)
    templates = rs.rand(10, 1, 28, 28).astype(np.float32)
    y = (rs.rand(n_synth) * 10).astype(np.int64)
    x = templates[y] + 0.25 * rs.randn(n_synth, 1, 28, 28).astype(np.float32)
    x = np.clip(x, 0.0, 1.0)
    cut = int(n_synth * 0.9)
    return (x[:cut], y[:cut].astype(np.float32),
            x[cut:], y[cut:].astype(np.float32))


def mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def lenet_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=50)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=500)
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=10)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def mlp_gluon():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Flatten(),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    return net


def lenet_gluon():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(20, kernel_size=5, activation="tanh"),
            gluon.nn.MaxPool2D(pool_size=2, strides=2),
            gluon.nn.Conv2D(50, kernel_size=5, activation="tanh"),
            gluon.nn.MaxPool2D(pool_size=2, strides=2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="tanh"),
            gluon.nn.Dense(10))
    return net


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    common.add_fit_args(parser)
    parser.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    parser.add_argument("--gluon", action="store_true",
                        help="train via Gluon blocks + Trainer")
    parser.add_argument("--data-dir", default="",
                        help="directory with MNIST idx files (synthetic "
                             "fallback when empty)")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    if "dist" in args.kv_store:
        # the coordination service must come up before ANY jax backend
        # touch (the reference's DMLC_ROLE bootstrap, tools/launch.py)
        from incubator_mxnet_tpu.parallel import dist
        dist.init_process()
    mx.random.seed(args.seed)

    tx, ty, vx, vy = load_mnist(args.data_dir)
    if "dist" in args.kv_store:
        # shard the training set by worker rank (the reference's
        # part_index/num_parts split) — no redundant compute across ranks
        from incubator_mxnet_tpu.parallel import dist
        tx, ty = tx[dist.rank()::dist.num_workers()], \
            ty[dist.rank()::dist.num_workers()]
    train_iter = mx.io.NDArrayIter(tx, ty, args.batch_size, shuffle=True,
                                   label_name="softmax_label")
    val_iter = mx.io.NDArrayIter(vx, vy, args.batch_size,
                                 label_name="softmax_label")
    if args.gluon:
        net = lenet_gluon() if args.network == "lenet" else mlp_gluon()
        net.hybridize()
        acc = common.fit_gluon(net, train_iter, val_iter, args)
    else:
        sym = lenet_symbol() if args.network == "lenet" else mlp_symbol()
        acc = common.fit_module(sym, train_iter, val_iter, args)
    print("validation accuracy: %.4f" % acc)


if __name__ == "__main__":
    main()
