"""Train an ImageNet-class convnet — the reference's headline workload
(example/image-classification/train_imagenet.py, the script behind every
BASELINE.md training row), rebuilt TPU-first.

Data: a RecordIO file through the full pipeline (ImageRecordIter: indexed
reader → threaded decode → PrefetchingIter) when ``--data-rec`` is given
— raw-tensor records from ``tools/im2rec.py --pack-raw`` stream without a
host JPEG decode; otherwise synthetic ImageNet-shaped batches (zero
egress here), same shapes, same loop.

Surfaces: default = the fused one-jit DataParallelTrainer (bf16 compute,
f32 master weights — the bench path); ``--module`` = Module.fit on the
symbolic graph; ``--gluon-trainer`` = the eager Gluon Trainer loop.

Usage:
    python train_imagenet.py --network resnet50 --batch-size 256
    python train_imagenet.py --data-rec data/imagenet_raw --num-epochs 1
    python train_imagenet.py --network resnet18 --image-shape 3,32,32 \
        --num-classes 10 --module     # CIFAR-shaped quick run
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
# make the in-repo package importable when run straight from a checkout
sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))
import common  # noqa: E402

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import gluon  # noqa: E402
from incubator_mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


NETWORKS = {
    "resnet18": vision.resnet18_v1,
    "resnet34": vision.resnet34_v1,
    "resnet50": vision.resnet50_v1,
    "resnet101": vision.resnet101_v1,
    "alexnet": vision.alexnet,
    "vgg11": vision.vgg11,
    "mobilenet": lambda **kw: vision.get_mobilenet(1.0, **kw),
}


def synthetic_iters(args, shape):
    """ImageNet-shaped random batches with class-dependent structure
    (rank-sharded under dist kvstores)."""
    rs = np.random.RandomState(3)
    n = args.batch_size * args.num_batches
    y = (rs.rand(n) * args.num_classes).astype(np.int64)
    x = rs.rand(n, *shape).astype(np.float32)
    # inject a weak class signal so accuracy is measurable
    x[np.arange(n), 0, 0, 0] = y / float(args.num_classes)
    cut = n - args.batch_size
    if cut <= 0:
        # single-batch runs: validate on the training batch rather than
        # silently reporting accuracy over an empty set
        cut = n
        vx, vy = x, y
    else:
        vx, vy = x[cut:], y[cut:]
    tx, ty = x[:cut], y[:cut]
    if "dist" in args.kv_store:
        from incubator_mxnet_tpu.parallel import dist
        tx, ty = tx[dist.rank()::dist.num_workers()], \
            ty[dist.rank()::dist.num_workers()]
    train = mx.io.NDArrayIter(tx, ty.astype(np.float32),
                              args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(vx, vy.astype(np.float32),
                            args.batch_size, label_name="softmax_label")
    return train, val


def record_iters(args, shape):
    """The real data plane: ImageRecordIter over .rec (+ .idx)."""
    rank, nw = 0, 1
    if "dist" in args.kv_store:
        from incubator_mxnet_tpu.parallel import dist
        rank, nw = dist.rank(), dist.num_workers()
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_rec + ".rec",
        path_imgidx=args.data_rec + ".idx",
        data_shape=tuple(shape), batch_size=args.batch_size,
        shuffle=True, dtype="uint8", aug_list=[],
        part_index=rank, num_parts=nw,     # rank-sharded, like the ref
        preprocess_threads=args.preprocess_threads,
        prefetch_buffer=args.prefetch_buffer, ctx=mx.cpu(0))
    val_rec = args.data_rec_val or args.data_rec
    val = mx.io.ImageRecordIter(
        path_imgrec=val_rec + ".rec", path_imgidx=val_rec + ".idx",
        data_shape=tuple(shape), batch_size=args.batch_size,
        dtype="uint8", aug_list=[],
        preprocess_threads=args.preprocess_threads,
        prefetch_buffer=args.prefetch_buffer, ctx=mx.cpu(0))
    return train, val


def symbol_convnet(num_classes):
    """Compact declarative convnet for the Module path (the Gluon model
    zoo drives the other surfaces; Symbol composition stays first-class,
    ref: train_imagenet.py's symbol_* modules)."""
    net = mx.sym.Variable("data")
    for i, filters in enumerate((32, 64, 128)):
        net = mx.sym.Convolution(net, kernel=(3, 3), stride=(2, 2),
                                 pad=(1, 1), num_filter=filters,
                                 name="conv%d" % i)
        net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn%d" % i)
        net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    parser = argparse.ArgumentParser(description="train imagenet-class nets")
    common.add_fit_args(parser)
    parser.add_argument("--network", default="resnet50",
                        choices=sorted(NETWORKS))
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--num-batches", type=int, default=8,
                        help="synthetic batches per epoch")
    parser.add_argument("--data-rec", default="",
                        help="RecordIO prefix (expects .rec and .idx); "
                             "synthetic data when empty")
    parser.add_argument("--data-rec-val", default="")
    parser.add_argument("--preprocess-threads", type=int, default=4)
    parser.add_argument("--prefetch-buffer", type=int, default=4)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--module", action="store_true",
                        help="train via Module.fit on the Symbol graph")
    parser.add_argument("--gluon-trainer", action="store_true",
                        help="train via the eager Gluon Trainer loop")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    if "dist" in args.kv_store:
        # the coordination service must come up before ANY jax backend
        # touch (the reference's DMLC_ROLE bootstrap, tools/launch.py)
        from incubator_mxnet_tpu.parallel import dist
        dist.init_process()
    mx.random.seed(args.seed)
    shape = tuple(int(s) for s in args.image_shape.split(","))

    if args.data_rec:
        train_iter, val_iter = record_iters(args, shape)
    else:
        train_iter, val_iter = synthetic_iters(args, shape)

    if args.module:
        sym = symbol_convnet(args.num_classes)
        acc = common.fit_module(sym, train_iter, val_iter, args)
    elif args.gluon_trainer:
        net = NETWORKS[args.network](classes=args.num_classes)
        net.hybridize()
        acc = common.fit_gluon(net, train_iter, val_iter, args)
    else:
        net = NETWORKS[args.network](classes=args.num_classes)
        acc = common.fit_fused(net, train_iter, val_iter, args,
                               dtype=args.dtype)
    print("validation accuracy: %.4f" % acc)


if __name__ == "__main__":
    main()
