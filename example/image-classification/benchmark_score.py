"""Inference benchmark across the model zoo — the TPU counterpart of the
reference's scoring sweep (ref: example/image-classification/
benchmark_score.py:1-66, numbers in docs/faq/perf.md:122-144).

The TPU-native inference path: a hybridized Gluon zoo model — the whole
forward compiles to ONE XLA program via CachedOp — driven batch after
batch.  Sync discipline: the device stream executes dispatches in order,
so a host fetch of (one element of) the LAST batch's output bounds the
whole timed region; ``wait_to_read``/``block_until_ready`` alone does
not reliably synchronize through the axon tunnel (bench.py discipline).
bf16 by default: inference has no master-weight concern and the MXU
doubles bf16 throughput.

Usage:
    python benchmark_score.py                  # full sweep, JSON lines
    python benchmark_score.py --network resnet-50 --batch-size 32
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.gluon.model_zoo import vision  # noqa: E402

# network name (reference spelling) -> (zoo factory, input size)
NETWORKS = {
    "alexnet": ("alexnet", 224),
    "vgg-16": ("vgg16", 224),
    "inception-v3": ("inception_v3", 299),
    "resnet-50": ("resnet50_v1", 224),
    "resnet-152": ("resnet152_v1", 224),
    "mobilenet-1.0": ("mobilenet1_0", 224),
    "densenet-121": ("densenet121", 224),
    "squeezenet-1.0": ("squeezenet1_0", 224),
}


def score(network, batch_size, num_batches=10, dtype="bfloat16"):
    """img/s for one (network, batch) point; warm-up excluded."""
    factory, size = NETWORKS[network]
    mx.random.seed(0)
    net = getattr(vision, factory)(classes=1000)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    if dtype not in ("float32", "none", None):
        net.cast(dtype)
    net.hybridize()

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.uniform(-1, 1, (batch_size, 3, size, size))
                    .astype(np.float32))
    if dtype not in ("float32", "none", None):
        x = x.astype(dtype)

    def sync(out):
        # in-order device stream: fetching one element of the last output
        # bounds every dispatch before it
        return float(out.reshape((-1,))[0:1].asnumpy()[0])

    for _ in range(5):                     # warm-up (includes compile)
        out = net(x)
    sync(out)

    t0 = time.perf_counter()
    for _ in range(num_batches):
        out = net(x)
    sync(out)                              # host fetch = true sync
    dt = time.perf_counter() - t0
    return num_batches * batch_size / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default=None,
                   help="one of %s (default: all)" % ", ".join(NETWORKS))
    p.add_argument("--batch-size", type=int, default=0,
                   help="single batch size (default: sweep 1 and 32)")
    p.add_argument("--num-batches", type=int, default=10)
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()

    networks = [args.network] if args.network else list(NETWORKS)
    batches = [args.batch_size] if args.batch_size else [1, 32]
    for network in networks:
        for b in batches:
            img_s = score(network, b, args.num_batches, args.dtype)
            print(json.dumps({
                "metric": "inference_imgs_per_sec", "network": network,
                "batch_size": b, "value": round(img_s, 2), "unit": "img/s",
                "dtype": args.dtype,
            }), flush=True)


if __name__ == "__main__":
    main()
