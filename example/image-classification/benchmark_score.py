"""Inference benchmark across the model zoo — the TPU counterpart of the
reference's scoring sweep (ref: example/image-classification/
benchmark_score.py:1-66, numbers in docs/faq/perf.md:122-144).

Two measurement modes:

* ``--mode steady`` (default): CHIP-TRUE.  The hybridized forward is
  functionalized (``gluon.block.functionalize``) and ``lax.scan``-chained
  K times inside ONE XLA program, each iteration's input perturbed by a
  scalar probe of the previous iteration's output — a data dependence
  XLA can neither hoist out of the loop (LICM needs loop-invariance) nor
  batch away, so the timed region is K back-to-back forwards with ONE
  dispatch.  This defeats the axon tunnel's per-dispatch floor (~100 ms+,
  docs/perf_analysis_r03.md) that made the round-4 eager sweep read
  resnet-152 faster than resnet-50: transport noise divides by K.
* ``--mode eager``: one dispatch per batch through the stock
  CachedOp path — measures the FRAMEWORK serving path including
  per-call overhead (the number a latency-sensitive user sees), kept
  for comparability with the round-4 table.

Sync discipline (both modes): host fetch of a value data-dependent on
all timed work; ``block_until_ready`` alone does not reliably sync
through the axon tunnel.  bf16 by default: inference has no
master-weight concern and the MXU doubles bf16 throughput.

Usage:
    python benchmark_score.py                  # full sweep, JSON lines
    python benchmark_score.py --network resnet-50 --batch-size 32
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.gluon.model_zoo import vision  # noqa: E402

# network name (reference spelling) -> (zoo factory, input size)
NETWORKS = {
    "alexnet": ("alexnet", 224),
    "vgg-16": ("vgg16", 224),
    "inception-v3": ("inception_v3", 299),
    "resnet-50": ("resnet50_v1", 224),
    "resnet-152": ("resnet152_v1", 224),
    "mobilenet-1.0": ("mobilenet1_0", 224),
    "densenet-121": ("densenet121", 224),
    "squeezenet-1.0": ("squeezenet1_0", 224),
}


def _build(network, batch_size, dtype):
    factory, size = NETWORKS[network]
    mx.random.seed(0)
    net = getattr(vision, factory)(classes=1000)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    if dtype not in ("float32", "none", None):
        net.cast(dtype)
    net.hybridize()
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.uniform(-1, 1, (batch_size, 3, size, size))
                    .astype(np.float32))
    if dtype not in ("float32", "none", None):
        x = x.astype(dtype)
    return net, x


def score_steady(network, batch_size, chain=100, repeats=2,
                 dtype="bfloat16", fn_params=None, x=None):
    """img/s by the TWO-POINT chained method: time a K-chain and a
    2K-chain program and divide the K extra forwards by the time
    DIFFERENCE — the per-dispatch transport floor appears in both
    measurements and cancels exactly, so even batch-1 points measure the
    chip (a single-chain rate still carries floor/(K·t) bias, which made
    resnet-152 read faster than resnet-50 at b1).  ``fn_params``/``x``
    override the model (used by the quantization bench to time an
    already-transformed forward through the identical harness)."""
    import jax
    import jax.numpy as jnp

    if fn_params is None:
        from incubator_mxnet_tpu.gluon.block import functionalize
        net, xin = _build(network, batch_size, dtype)
        fn, params = functionalize(net, xin)
        x = xin._read()
    else:
        fn, params = fn_params

    def make(length):
        @jax.jit
        def chained(params, x0):
            def body(carry, _):
                out = fn(params, carry)
                # a scalar probe of THIS output is written INTO the
                # carried input (dynamic_update_slice, element [0...],
                # sub-ULP value): the op chain stays strictly serial and
                # nothing hoists.  An additive scalar probe is NOT safe:
                # the model's FIRST layer is linear, so XLA distributes
                # fn1(x0+s) = fn1(x0) + s*fn1(1) and hoists the
                # loop-invariant fn1(x0) out of the scan (see
                # benchmark_op.bench_serial_shape's HLO-verified notes).
                p = out.reshape(-1)[0].astype(jnp.float32)
                nxt = jax.lax.dynamic_update_slice(
                    carry, (p * 1e-20).astype(x0.dtype).reshape(
                        (1,) * x0.ndim), (0,) * x0.ndim)
                return nxt, p
            _, probes = jax.lax.scan(body, x0, None, length=length)
            return probes.sum()
        return chained

    def best_time(fn_c):
        float(fn_c(params, x))               # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(fn_c(params, x))           # host fetch = true sync
            best = min(best, time.perf_counter() - t0)
        return best

    # adaptive: when the K-vs-2K difference is inside dispatch jitter
    # (small model × small batch), quadruple K until the chained compute
    # clearly dominates — otherwise b1 rows read noise, up to 1/eps
    while True:
        t1 = best_time(make(chain))
        t2 = best_time(make(2 * chain))
        if t2 - t1 > 0.33 * t1 or chain >= 6400:
            break
        chain *= 4
    return chain * batch_size / max(t2 - t1, 1e-9)


def score_eager(network, batch_size, num_batches=10, dtype="bfloat16"):
    """img/s, one dispatch per batch (includes per-call overhead)."""
    net, x = _build(network, batch_size, dtype)

    def sync(out):
        # in-order device stream: fetching one element of the last output
        # bounds every dispatch before it
        return float(out.reshape((-1,))[0:1].asnumpy()[0])

    for _ in range(5):                     # warm-up (includes compile)
        out = net(x)
    sync(out)

    t0 = time.perf_counter()
    for _ in range(num_batches):
        out = net(x)
    sync(out)                              # host fetch = true sync
    dt = time.perf_counter() - t0
    return num_batches * batch_size / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default=None,
                   help="one of %s (default: all)" % ", ".join(NETWORKS))
    p.add_argument("--batch-size", type=int, default=0,
                   help="single batch size (default: sweep 1 and 32)")
    p.add_argument("--mode", default="steady", choices=["steady", "eager"])
    p.add_argument("--chain", type=int, default=100,
                   help="forwards per dispatch in steady mode")
    p.add_argument("--num-batches", type=int, default=10,
                   help="batches to time in eager mode")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()

    networks = [args.network] if args.network else list(NETWORKS)
    batches = [args.batch_size] if args.batch_size else [1, 32]
    for network in networks:
        for b in batches:
            if args.mode == "steady":
                img_s = score_steady(network, b, args.chain,
                                     dtype=args.dtype)
            else:
                img_s = score_eager(network, b, args.num_batches,
                                    args.dtype)
            print(json.dumps({
                "metric": "inference_imgs_per_sec", "network": network,
                "batch_size": b, "value": round(img_s, 2), "unit": "img/s",
                "dtype": args.dtype, "mode": args.mode,
            }), flush=True)


if __name__ == "__main__":
    main()
