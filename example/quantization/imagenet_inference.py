"""Quantized vs bf16 model inference — the INT8 serving proof.

TPU counterpart of the reference's quantization example pair
(ref: example/quantization/imagenet_gen_qsym.py:1 — calibrated symbol
generation; example/quantization/imagenet_inference.py:1 — quantized vs
fp32 inference timing): builds the symbolic ResNet, folds BatchNorm into
the convs (contrib.quantization.fold_batchnorm — the role the
reference's fused MKLDNN subgraphs play), calibrates + quantizes the
folded graph, then times bf16 vs int8 through the steady-state chained
harness (K forwards per dispatch, the benchmark_score.py --mode steady
discipline) so the ratio measures the chip, not the transport.

Accuracy is reported as int8-vs-f32 top-1 agreement on held-out
synthetic batches (no ImageNet in this environment; the subsystem's
≤1%-drop accuracy bar is separately enforced on a trained model in
tests/test_quantization.py).

Prints JSON lines; the last line carries the int8/bf16 speedup.

Usage:
    python imagenet_inference.py                     # resnet-50, b 1+32
    python imagenet_inference.py --num-layers 18 --batch-size 32 \
        --calib-mode entropy
"""
import argparse
import importlib.util
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, io  # noqa: E402
from incubator_mxnet_tpu.contrib import quantization as qz  # noqa: E402
from incubator_mxnet_tpu.ndarray import NDArray  # noqa: E402


def _load_example(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), "..", relpath))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _load_resnet():
    return _load_example(os.path.join("image-classification", "symbols",
                                      "resnet.py"), "sym_resnet")


def _host_init(pred, data_shape, seed=0):
    """MSRA-scaled host-side init (activations stay O(1) through the
    stack, so calibration ranges are realistic; device-RNG init over the
    axon tunnel would cost minutes — bench_transformer.py HostXavier)."""
    rs = np.random.RandomState(seed)
    shapes, _, aux_shapes = pred.infer_shape(data=data_shape)
    args, aux = {}, {}
    for n, s in zip(pred.list_arguments(), shapes):
        if n == "data":
            continue
        if "weight" in n:
            fan_in = int(np.prod(s[1:]))
            v = rs.randn(*s).astype(np.float32) * np.sqrt(2.0 / fan_in)
        elif "gamma" in n:
            v = np.ones(s, np.float32)
        else:                       # beta / bias
            v = np.zeros(s, np.float32)
        args[n] = mx.nd.array(v)
    for n, s in zip(pred.list_auxiliary_states(), aux_shapes):
        aux[n] = mx.nd.array(np.ones(s, np.float32) if "var" in n
                             else np.zeros(s, np.float32))
    return args, aux


def _eval_fn(sym, cast=None):
    """Pure jittable fn(param_vals, x) over a Symbol's eval_dict trace."""
    def fn(param_vals, x):
        merged = {k: NDArray(v) for k, v in param_vals.items()}
        merged["data"] = NDArray(x)
        with autograd._scope(recording=False, training=False):
            out = sym.eval_dict(merged)
        out = out[0] if isinstance(out, (list, tuple)) else out
        return out._read()
    return fn


_BENCH_SCORE = None


def steady_rate(fn, param_vals, x, chain=50, repeats=2):
    """Images/sec through benchmark_score's steady harness — ONE timing
    discipline for plain and quantized serving (its fn_params/x hooks
    exist for exactly this caller)."""
    global _BENCH_SCORE
    if _BENCH_SCORE is None:
        _BENCH_SCORE = _load_example(
            os.path.join("image-classification", "benchmark_score.py"),
            "bench_score_q")
    return _BENCH_SCORE.score_steady(None, x.shape[0], chain=chain,
                                     repeats=repeats,
                                     fn_params=(fn, param_vals), x=x)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=0,
                   help="single batch (default: sweep 1 and 32)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--chain", type=int, default=50)
    p.add_argument("--calib-mode", default="naive",
                   choices=["none", "naive", "entropy"])
    p.add_argument("--num-calib-batches", type=int, default=4)
    p.add_argument("--calib-batch-size", type=int, default=8)
    args = p.parse_args()

    import jax.numpy as jnp

    resnet = _load_resnet()
    size = args.image_size
    net = resnet.get_symbol(num_classes=1000, num_layers=args.num_layers)
    pred = net.get_internals()["fc1_output"]
    data_shape = (args.calib_batch_size, 3, size, size)
    arg_params, aux_params = _host_init(pred, data_shape)

    rs = np.random.RandomState(1)
    calib = rs.uniform(-1, 1, (args.num_calib_batches
                               * args.calib_batch_size, 3, size, size)) \
        .astype(np.float32)

    fsym, fargs, faux = qz.fold_batchnorm(pred, arg_params, aux_params)
    assert not faux, "BN must fold away for the int8 serving graph"
    calib_mode = args.calib_mode
    qsym, qargs, _ = qz.quantize_model(
        fsym, fargs, {}, calib_mode=calib_mode,
        calib_data=io.NDArrayIter(data=calib,
                                  batch_size=args.calib_batch_size),
        num_calib_examples=len(calib))

    # held-out agreement (f32 folded graph is the reference output)
    xa = mx.nd.array(rs.uniform(-1, 1, (16, 3, size, size))
                     .astype(np.float32))
    ref = fsym.bind(mx.cpu(), {**fargs, "data": xa},
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    got = qsym.bind(mx.cpu(), {**qargs, "data": xa},
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    agree = float((ref.argmax(1) == got.argmax(1)).mean())
    # random-init logits cluster near zero, so agreement underestimates
    # real-model fidelity; relative logit error is scale-free evidence
    rel_err = float(np.abs(got - ref).mean() / (np.abs(ref).std() + 1e-9))

    bf16_fn = _eval_fn(fsym)
    bf16_params = {k: v._read().astype(jnp.bfloat16)
                   for k, v in fargs.items()}
    q_fn = _eval_fn(qsym)
    q_params = {k: v._read() for k, v in qargs.items()}

    batches = [args.batch_size] if args.batch_size else [1, 32]
    for b in batches:
        x = rs.uniform(-1, 1, (b, 3, size, size)).astype(np.float32)
        r_bf16 = steady_rate(bf16_fn, bf16_params,
                             jnp.asarray(x, jnp.bfloat16), args.chain)
        r_int8 = steady_rate(q_fn, q_params, jnp.asarray(x), args.chain)
        print(json.dumps({
            "metric": "quantized_inference_imgs_per_sec",
            "network": "resnet-%d" % args.num_layers, "batch_size": b,
            "bf16_imgs_per_sec": round(r_bf16, 2),
            "int8_imgs_per_sec": round(r_int8, 2),
            "int8_speedup_vs_bf16": round(r_int8 / r_bf16, 3),
            "top1_agreement_int8_vs_f32": round(agree, 4),
            "logit_rel_err_int8_vs_f32": round(rel_err, 4),
            "calib_mode": calib_mode, "chain": args.chain,
        }), flush=True)


if __name__ == "__main__":
    main()
