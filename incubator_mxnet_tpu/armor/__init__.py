"""graftarmor — fault injection, self-healing RPC, atomic checkpointing.

The robustness layer (ISSUE 15 / docs/robustness.md), four pieces:

* :mod:`.faults` — ``GRAFT_FAULTS`` deterministic fault injection into
  the real PS/collective/dataloader/serving code paths.
* the self-healing PS wire lives in :mod:`..parallel.ps` (per-call
  timeouts, reconnect + bounded backoff, idempotent retry ids) — armor
  supplies its typed failures and chaos sites.
* :mod:`.checkpoint` — atomic step-consistent snapshot/restore of
  params + optimizer state + step + RNG, with auto-resume.
* typed hang escalation rides :mod:`..telemetry.watchdog`
  (``GRAFT_WATCHDOG_ESCALATE``) using :mod:`.errors`.

Everything is off by default and bit-inert when off; ``python -m
incubator_mxnet_tpu.armor --selftest`` proves the machinery end to end.
"""
from __future__ import annotations

from .errors import (ArmorError, FaultInjectedError, PSUnavailableError,
                     CollectiveTimeoutError, CheckpointCorruptError,
                     ShardOwnershipError, MembershipChangedError,
                     QuiesceTimeoutError)
from .faults import fault_point, configure, reset, active_rules, set_rank

__all__ = [
    "ArmorError", "FaultInjectedError", "PSUnavailableError",
    "CollectiveTimeoutError", "CheckpointCorruptError",
    "ShardOwnershipError", "MembershipChangedError", "QuiesceTimeoutError",
    "fault_point", "configure", "reset", "active_rules", "set_rank",
]
