"""graftarmor CLI.

    python -m incubator_mxnet_tpu.armor --selftest
        Lint smoke tier for the robustness layer:

        * fault grammar — n=/every=/p=/ctx/rank selectors fire
          deterministically (two replays of a seeded probabilistic rule
          must produce the identical fire sequence) and every fire lands
          a ``fault_injected`` event in the flight recorder;
        * PS wire self-healing — against a REAL ParameterServer +
          PSClient pair: a dropped reply retries and is deduplicated
          server-side (the ambiguous-disconnect idempotence contract),
          an injected disconnect reconnects, an exhausted budget raises
          typed ``PSUnavailableError``;
        * atomic checkpoint — a gluon Trainer snapshot round-trips
          bit-exactly (params + momentum state + RNG), a corrupted
          newest snapshot is skipped in favor of the previous valid one,
          and every corruption mode raises ``CheckpointCorruptError``;
        * hang escalation — a watchdog trip on a stuck ps_* bracket
          delivers ``PSUnavailableError`` into the waiting thread naming
          the dead rank, and the trip dump passes schema validation.

        Exit 1 on any regression.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

import numpy as np

_ENV_KEYS = ("GRAFT_FAULTS", "GRAFT_RPC_TIMEOUT", "GRAFT_RPC_RETRIES",
             "GRAFT_RPC_BACKOFF_MS", "GRAFT_WATCHDOG_ESCALATE",
             "GRAFT_CHECKPOINT_EVERY")


def _fault_grammar(check):
    from . import faults
    from .errors import FaultInjectedError

    def fires(spec, site, n, **ctx):
        faults.configure(spec)
        out = []
        for _ in range(n):
            try:
                faults.fault_point(site, **ctx)
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out

    check(fires("a.b:error:n=2", "a.b", 4) == [False, True, False, False],
          "n= selector must fire exactly on the 2nd arrival, once")
    check(fires("a.*:error:every=2", "a.x", 6)
          == [False, True, False, True, False, True],
          "every= selector (prefix site) must fire on arrivals 2/4/6")
    seq1 = fires("s.p:error:p=0.5:seed=7:times=100", "s.p", 20)
    seq2 = fires("s.p:error:p=0.5:seed=7:times=100", "s.p", 20)
    check(seq1 == seq2 and any(seq1) and not all(seq1),
          "seeded p= replay must be deterministic and non-degenerate")
    check(fires("c.s:error:cmd=push", "c.s", 3, cmd="pull")
          == [False] * 3, "ctx mismatch (cmd=pull) must never fire")
    check(fires("c.s:error:cmd=push", "c.s", 2, cmd="push")
          == [True, True], "ctx match (cmd=push) must fire")
    faults.set_rank(1)
    check(fires("r.s:error:rank=0", "r.s", 2) == [False, False],
          "rank filter must gate on set_rank")
    faults.set_rank(0)
    check(fires("r.s:error:rank=0:n=1", "r.s", 2) == [True, False],
          "rank filter must pass on the matching rank")
    faults.set_rank(None)
    faults.configure("d.s:delay:ms=40:n=1")
    t0 = time.perf_counter()
    faults.fault_point("d.s")
    check(time.perf_counter() - t0 >= 0.03,
          "delay kind must sleep ~ms at the site")
    faults.reset()
    check(faults.fault_point("a.b") is None and not faults.active_rules(),
          "reset must disarm every rule")


def _ps_wire(check):
    from ..parallel import ps
    from ..telemetry import blackbox
    from . import faults
    from .errors import PSUnavailableError

    srv = ps.ParameterServer(host="127.0.0.1")
    client = ps.PSClient(srv.address)
    try:
        client.init({"w": np.zeros(4, np.float32)})
        client.push({"w": np.ones(4, np.float32)})
        check(float(client.pull(["w"])["w"][0]) == 1.0,
              "clean push/pull must round-trip")

        # ambiguous disconnect: the reply to an APPLIED push is dropped;
        # the retried request (same monotonic id) must be deduplicated
        # server-side, not applied twice
        faults.configure("ps.recv:drop:n=1:cmd=push")
        client.push({"w": np.ones(4, np.float32)})
        got = float(client.pull(["w"])["w"][0])
        check(got == 2.0,
              "retried push after dropped reply applied %.1f times, "
              "want exactly once (idempotent dedup)" % (got - 1.0))

        faults.configure("ps.send:disconnect:n=1:cmd=push")
        client.push({"w": np.ones(4, np.float32)})
        check(float(client.pull(["w"])["w"][0]) == 3.0,
              "push across an injected disconnect must reconnect+retry")

        ev = [e for e in blackbox.events()
              if e.get("kind") == "fault_injected"]
        check(len(ev) >= 2
              and any(e["data"].get("site") == "ps.recv" for e in ev),
              "every injected fault must land in the flight recorder")

        faults.configure("ps.send:error:every=1:cmd=push")
        try:
            client.push({"w": np.ones(4, np.float32)})
            check(False, "exhausted retry budget must raise")
        except PSUnavailableError as exc:
            check(exc.cmd == "push" and exc.attempts == 3,
                  "PSUnavailableError must carry cmd/attempts "
                  "(got %r/%r)" % (exc.cmd, exc.attempts))
        faults.reset()
        client.heartbeat(0)
        check(client.dead_nodes(window=60.0) == [],
              "heartbeat must keep this worker off the dead list")
    finally:
        faults.reset()
        client.close()
        srv.shutdown()
    try:
        client.push({"w": np.ones(4, np.float32)})
        check(False, "a closed client must fail fast")
    except PSUnavailableError:
        pass


def _trainer(seed=3):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    import jax.numpy as jnp
    net = gluon.nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    rs = np.random.RandomState(seed)
    net(mx.nd.array(rs.randn(2, 6).astype(np.float32)))   # shape them
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer, rs


def _step(net, trainer, rs):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    x = mx.nd.array(rs.randn(2, 6).astype(np.float32))
    with autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    trainer.step(2)


def _param_bytes(net):
    return {name: np.asarray(p.data()._read()).tobytes()
            for name, p in net.collect_params().items()}


def _checkpoint(check):
    import jax.numpy as jnp
    from . import checkpoint as ckpt
    from .errors import CheckpointCorruptError
    from .. import random_state

    net, trainer, rs = _trainer()
    _step(net, trainer, rs)
    random_state.seed(1234)
    random_state.next_key()         # advance the counter: non-trivial RNG

    with tempfile.TemporaryDirectory(prefix="graftarmor-ckpt-") as d:
        cp = trainer.checkpointer(d, every=None, keep=3, emergency=False)
        try:
            cp.save(step=1)
            want = _param_bytes(net)
            want_rng = random_state.get_state()
            _step(net, trainer, rs)     # diverge: params + momentum move
            random_state.seed(999)
            cp.save(step=2)

            # corrupt the NEWEST snapshot: resume must fall back to the
            # last VALID one (step 1), not die and not load garbage
            p2 = cp._path(2)
            raw = bytearray(open(p2, "rb").read())
            raw[-3] ^= 0xFF
            with open(p2, "wb") as f:
                f.write(raw)
            try:
                ckpt.load_state(p2)
                check(False, "flipped byte must fail the sha256 check")
            except CheckpointCorruptError:
                pass
            step = cp.resume()
            check(step == 1, "resume must land on the last VALID "
                  "snapshot (got step %r, want 1)" % step)
            check(_param_bytes(net) == want,
                  "restored params must be bit-identical to the capture")
            check(random_state.get_state() == want_rng,
                  "restored RNG state must match the capture")

            # optimizer state (momentum) restored too: one more step from
            # the restored state must be bit-reproducible
            rs2 = np.random.RandomState(77)
            _step(net, trainer, rs2)
            after_a = _param_bytes(net)
            cp.resume()
            rs2 = np.random.RandomState(77)
            _step(net, trainer, rs2)
            check(_param_bytes(net) == after_a,
                  "step-after-resume must replay bit-identically "
                  "(momentum state restored)")

            for reason, mutate in [
                    ("truncated", lambda b: b[:20]),
                    ("bad magic", lambda b: b"XX" + b[2:]),
            ]:
                p1 = cp._path(1)
                good = open(p1, "rb").read()
                with open(p1 + ".bad", "wb") as f:
                    f.write(mutate(good))
                try:
                    ckpt.load_state(p1 + ".bad")
                    check(False, "%s snapshot must not load" % reason)
                except CheckpointCorruptError:
                    pass
            check(ckpt.load_state(cp._path(1)).get("step") == 1,
                  "the valid snapshot must still load after the tests")
        finally:
            cp.close()


def _escalation(check):
    from ..telemetry import blackbox, watchdog
    from .errors import PSUnavailableError

    os.environ["GRAFT_WATCHDOG_ESCALATE"] = "1"
    watchdog.register_dead_nodes_provider(lambda: [3])
    caught = []
    ready = threading.Event()

    def victim():
        try:
            with blackbox.collective("ps_push", n_keys=1):
                ready.set()
                for _ in range(200):    # sleeps in short Python-bytecode
                    time.sleep(0.02)    # hops so the async raise lands
        except PSUnavailableError as exc:
            caught.append(exc)

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    ready.wait(5.0)
    deadline = time.time() + 0.25
    with tempfile.TemporaryDirectory(prefix="graftarmor-wd-") as d:
        path = os.path.join(d, "trip.json")
        wd = watchdog.Watchdog(timeout=0.2, path=path)
        while time.time() < deadline:
            time.sleep(0.02)
        wd.poll()
        t.join(5.0)
        check(bool(caught), "escalation must deliver the typed error "
              "into the waiting thread")
        if caught:
            check(caught[0].dead_ranks == (3,),
                  "escalated error must name the dead rank "
                  "(got %r)" % (caught[0].dead_ranks,))
        import json
        with open(path) as f:
            doc = json.load(f)
        problems = blackbox.validate_dump(doc)
        check(not problems, "trip dump must validate: %s" % problems)
        check(doc.get("watchdog", {}).get("dead_ranks") == [3],
              "trip dump must carry the dead-rank table")
    watchdog.register_dead_nodes_provider(None)


def selftest():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ..telemetry import blackbox
    from . import faults

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)
            print("graftarmor selftest FAIL: %s" % msg, file=sys.stderr)

    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    prev_enabled = blackbox._enabled_override
    blackbox.set_enabled(True)
    os.environ["GRAFT_RPC_TIMEOUT"] = "10"
    os.environ["GRAFT_RPC_RETRIES"] = "2"
    os.environ["GRAFT_RPC_BACKOFF_MS"] = "1"
    try:
        _fault_grammar(check)
        _ps_wire(check)
        _checkpoint(check)
        _escalation(check)
    finally:
        faults.reset()
        blackbox.set_enabled(prev_enabled)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if failures:
        print("graftarmor selftest: %d failure(s)" % len(failures),
              file=sys.stderr)
        return 1
    print("graftarmor selftest OK (fault grammar deterministic, PS wire "
          "self-heals with idempotent retries, checkpoints atomic + "
          "last-valid resume, watchdog escalation typed + dead-rank "
          "attribution)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m incubator_mxnet_tpu.armor")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
