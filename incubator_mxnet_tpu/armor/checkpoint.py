"""graftarmor atomic checkpoint / auto-resume.

A checkpoint is a *step-consistent* snapshot — params + optimizer
states + step counter + RNG captured only after every in-flight
reduce/pull handle has drained, so no torn bucket is ever persisted —
written **tmp-then-rename** so a crash mid-write can never destroy the
previous good snapshot, and self-validating: the payload rides behind a
fixed magic header carrying its own SHA-256, and a human-readable
``.manifest.json`` sidecar mirrors the hash for external tooling.

Layout (one file per snapshot)::

    GRAFTARMOR1\\n            magic (12 bytes)
    <sha256: 32 bytes>        digest of the payload
    <length: 8 bytes LE>      payload byte count
    <payload>                 pickled state dict (format graft-armor/1)

Entry points:

* :func:`save_state` / :func:`load_state` — raw state dicts, validated;
  loads raise :class:`~.errors.CheckpointCorruptError` on a bad magic,
  hash mismatch, or truncation (never a pickle traceback).
* :func:`snapshot_trainer` / :func:`restore_trainer` — capture/restore
  a ``gluon.Trainer`` (params, local or store-side Updater states,
  RNG).  dist_async optimizer state lives on the parameter server and
  is not captured (the same restriction ``Trainer.save_states`` keeps);
  the restored *weights* re-seed the server through the normal
  ``kvstore.init`` first-push-wins path on restart.
* :class:`Checkpointer` — periodic ``GRAFT_CHECKPOINT_EVERY`` saves
  into a directory of ``ckpt-<step>.armor`` files, ``resume()`` from
  the newest *valid* one (corrupt/truncated snapshots are skipped, not
  fatal), and a best-effort emergency snapshot hooked into the flight
  recorder's SIGTERM chain.

Everything here is inert unless called: no env var is read at import,
and a Trainer without a Checkpointer never touches this module.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import struct
import time

import numpy as np

from .errors import CheckpointCorruptError, ShardOwnershipError

__all__ = ["FORMAT", "save_state", "load_state", "manifest_of",
           "snapshot_trainer", "restore_trainer", "Checkpointer",
           "fast_forward", "configured_every"]

FORMAT = "graft-armor/1"
_MAGIC = b"GRAFTARMOR1\n"
_LEN = struct.Struct("<Q")


def configured_every():
    """GRAFT_CHECKPOINT_EVERY in steps, or None when unset/invalid."""
    raw = os.environ.get("GRAFT_CHECKPOINT_EVERY", "").strip()
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None


# -- the wire format --------------------------------------------------------

def save_state(path, state):
    """Atomically persist one state dict: serialize, hash, write to a
    same-directory tmp file, fsync, ``os.replace`` — readers only ever
    see the old snapshot or the complete new one.  Returns the manifest
    dict (also written to ``<path>.manifest.json``)."""
    state = dict(state, format=FORMAT)
    payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(payload).digest()
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(digest)
        f.write(_LEN.pack(len(payload)))
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    manifest = {"format": FORMAT, "sha256": digest.hex(),
                "nbytes": len(payload), "step": state.get("step"),
                "saved_at": time.time(),
                "params": sorted(state.get("params", {}))}
    mtmp = "%s.manifest.json.tmp.%d" % (path, os.getpid())
    try:
        with open(mtmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        os.replace(mtmp, path + ".manifest.json")
    except OSError:
        pass        # the sidecar is informational; the snapshot is whole
    from ..telemetry import blackbox as _blackbox
    _blackbox.record("checkpoint_saved", path=str(path),
                     step=state.get("step"), nbytes=len(payload))
    return manifest


def load_state(path):
    """Load + validate one snapshot.  Every corruption mode — missing
    file, bad magic, short read, hash mismatch, unpicklable payload,
    wrong format tag — surfaces as :class:`CheckpointCorruptError` with
    the reason, so resume loops can skip to an older snapshot."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        raise CheckpointCorruptError(path, "unreadable: %s" % exc)
    if not raw.startswith(_MAGIC):
        raise CheckpointCorruptError(path, "bad magic (not an armor "
                                     "checkpoint)")
    head = len(_MAGIC)
    if len(raw) < head + 32 + _LEN.size:
        raise CheckpointCorruptError(path, "truncated header")
    digest = raw[head:head + 32]
    (n,) = _LEN.unpack(raw[head + 32:head + 32 + _LEN.size])
    payload = raw[head + 32 + _LEN.size:]
    if len(payload) != n:
        raise CheckpointCorruptError(
            path, "truncated payload (%d of %d bytes)" % (len(payload), n))
    if hashlib.sha256(payload).digest() != digest:
        raise CheckpointCorruptError(path, "sha256 mismatch")
    try:
        state = pickle.loads(payload)
    except Exception as exc:
        raise CheckpointCorruptError(path, "unpicklable payload: %r" % exc)
    if not isinstance(state, dict) or state.get("format") != FORMAT:
        raise CheckpointCorruptError(
            path, "format is %r, expected %r"
            % (state.get("format") if isinstance(state, dict) else None,
               FORMAT))
    return state


def manifest_of(path):
    """The sidecar manifest (or None) — tooling convenience."""
    try:
        with open(path + ".manifest.json") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- Trainer capture/restore ------------------------------------------------

def _drain(trainer):
    """Settle every in-flight handle the trainer may hold so the capture
    is step-consistent: outstanding duplex weight pulls, then queued
    dist_async pushes (read-your-writes against the parameter server)."""
    sched = getattr(trainer, "_pull_scheduler", None)
    if sched is not None:
        sched.finish()
    kv = getattr(trainer, "_kvstore_obj", None)
    drain = getattr(kv, "_drain_pushes", None)
    if drain is not None:
        drain()
    return kv


def _updater_states(trainer):
    """The optimizer-state bytes this process owns, or None (dist_async:
    state lives on the parameter server — same save_states restriction)."""
    if getattr(trainer, "_kv_initialized", False) \
            and getattr(trainer, "_update_on_kvstore", False):
        updater = trainer._kvstore_obj._updater
        if updater is None:
            return None
        return updater.get_states(dump_optimizer=True)
    return trainer._updaters[0].get_states(dump_optimizer=True)


def snapshot_trainer(trainer, step, extra=None):
    """Build the state dict for one trainer: drains first, then pulls
    authoritative weights from a dist_async parameter server (the local
    mirror may be stale), then captures params/optimizer/RNG/step."""
    from .. import random_state as _random_state
    kv = _drain(trainer)
    if kv is not None and getattr(kv, "_ps", None) is not None:
        # dist_async: the SERVER holds the weights; refresh local copies
        # so the snapshot captures what training actually converged to
        keys = [i for i in range(len(trainer._params))]
        kv.pull(keys, [p.list_data() for p in trainer._params])
    params = {}
    for p in trainer._params:
        params[p.name] = np.asarray(p.list_data()[0]._read())
    shard = getattr(trainer, "_zero_spec", None)
    shard = shard() if callable(shard) else None
    member = getattr(trainer, "_membership", None)
    if member is not None:
        epoch = int(member.epoch)
    else:
        from ..analysis import lockstep as _lockstep
        epoch = int(_lockstep.epoch())
    state = {
        "format": FORMAT,
        "step": int(step),
        "params": params,
        "optimizer": None if shard else _updater_states(trainer),
        "rng": _random_state.get_state(),
        "saved_at": time.time(),
        "membership_epoch": epoch,
        "extra": dict(extra or {}),
    }
    if shard is not None:
        # ZeRO-1: optimizer state is partitioned by bucket ownership —
        # capture every local updater's shard (plus its error-feedback
        # residuals, which live in the same store) and the layout spec
        # so restore can refuse a mismatched topology.
        state["shard"] = dict(shard)
        state["optimizer_shards"] = [u.get_states(dump_optimizer=True)
                                     for u in trainer._updaters]
    return state


def restore_trainer(trainer, state):
    """Write a snapshot back onto a trainer: params to every context
    replica, optimizer states to the local updaters (or the store-side
    updater when it owns the update), RNG to this thread.  Restoring
    BEFORE the first step re-seeds dist stores through the normal
    ``_init_kvstore`` broadcast/init path."""
    import jax.numpy as jnp
    from .. import random_state as _random_state
    saved_shard = state.get("shard")
    cur = getattr(trainer, "_zero_spec", None)
    cur_shard = cur() if callable(cur) else None
    repartition = False
    if (saved_shard or None) != (dict(cur_shard) if cur_shard else None):
        from .. import elastic as _elastic
        same_axis = (saved_shard is not None and cur_shard is not None
                     and saved_shard.get("axis") == cur_shard.get("axis"))
        if _elastic.enabled() and same_axis:
            # graftelastic: the world size changed across a membership
            # epoch — re-partition the shard blobs deterministically
            # instead of refusing.  Ownership under ZeRO-1 is lazy
            # (sync_state_context rehydrates only the indices the NEW
            # shard map assigns each updater), so the merged state dict
            # restores safely on every updater.
            repartition = True
        else:
            # refuse BEFORE touching anything: a sharded snapshot on an
            # unsharded trainer (or vice versa, or a changed shard AXIS)
            # would restore at most one shard's optimizer state
            raise ShardOwnershipError(saved_shard, cur_shard,
                                      epoch=state.get("membership_epoch"))
    params = state.get("params", {})
    by_name = {p.name: p for p in trainer._params}
    missing = sorted(set(by_name) - set(params))
    if missing:
        raise CheckpointCorruptError(
            "<state>", "snapshot lacks params: %s" % missing[:5])
    from .. import engine as _engine
    for name, val in params.items():
        p = by_name.get(name)
        if p is None:
            continue            # extra param in snapshot: ignore
        for d in p.list_data():
            # colocate: each replica keeps its committed device — a bare
            # device_put would un-commit and break multi-ctx fused jits
            d._write(_engine.colocate(jnp.asarray(val).astype(d.dtype),
                                      d._read()))
    if saved_shard is not None:
        shards = state.get("optimizer_shards") or []
        if repartition:
            from ..elastic.membership import repartition_shard_states
            shards = repartition_shard_states(shards,
                                              len(trainer._updaters))
        if len(shards) != len(trainer._updaters):
            raise CheckpointCorruptError(
                "<state>", "snapshot has %d optimizer shards, trainer "
                "has %d updaters" % (len(shards), len(trainer._updaters)))
        for updater, blob in zip(trainer._updaters, shards):
            updater.set_states(blob)
    opt_bytes = state.get("optimizer")
    if opt_bytes is not None:
        if getattr(trainer, "_kv_initialized", False) \
                and getattr(trainer, "_update_on_kvstore", False) \
                and trainer._kvstore_obj._updater is not None:
            trainer._kvstore_obj._updater.set_states(opt_bytes)
        else:
            for updater in trainer._updaters:
                updater.set_states(opt_bytes)
    rng = state.get("rng")
    if rng is not None:
        _random_state.set_state(rng)
    # NOTE: restore is a RESTART-time operation.  On dist stores the
    # restored local values reach the wire through the normal
    # ``_init_kvstore`` path (rank-0 broadcast on dist_sync; first-push
    # init on a fresh dist_async server) — restoring into a trainer
    # whose kvstore is already live only changes the local replicas,
    # exactly like any other user weight write between steps.
    return int(state.get("step", 0))


def fast_forward(data_iter, n):
    """Advance a data iterator ``n`` batches (the resume contract: the
    restored step has consumed the first ``n``).  Epoch boundaries are
    honored when the iterator exposes ``reset()`` (the io.DataIter
    protocol); a plain short iterable just stops early."""
    it = iter(data_iter)
    skipped = 0
    while skipped < n:
        try:
            next(it)
            skipped += 1
        except StopIteration:
            reset = getattr(data_iter, "reset", None)
            if reset is None:
                break
            reset()
            it = iter(data_iter)
    return skipped


class Checkpointer(object):
    """Periodic + emergency checkpointing for one trainer.

    ``step_end(step)`` is the training-loop hook: every
    ``GRAFT_CHECKPOINT_EVERY`` steps (or the ``every`` argument) it
    writes ``ckpt-<step>.armor`` into ``directory`` and prunes old
    snapshots down to ``keep``.  ``resume()`` restores the newest VALID
    snapshot (corrupt ones are skipped with a ring event, never fatal)
    and returns its step so the caller can fast-forward its data.  When
    ``emergency`` is on, a SIGTERM/SIGINT lands one last best-effort
    snapshot through the flight recorder's signal chain before the
    process dies."""

    def __init__(self, trainer, directory, every=None, keep=2,
                 emergency=True):
        from ..telemetry import blackbox as _blackbox
        self.trainer = trainer
        self.directory = str(directory)
        self.every = every if every is not None else configured_every()
        self.keep = max(1, int(keep))
        self.last_step = None
        self._emergency_hook = None
        os.makedirs(self.directory, exist_ok=True)
        if emergency:
            def _on_signal(signum, _self=self):
                _self.save(step=_self.last_step or 0,
                           tag="emergency")
            self._emergency_hook = _on_signal
            _blackbox.register_emergency(_on_signal)

    def close(self):
        from ..telemetry import blackbox as _blackbox
        if self._emergency_hook is not None:
            _blackbox.unregister_emergency(self._emergency_hook)
            self._emergency_hook = None

    # -- saving -------------------------------------------------------------
    def _path(self, step, tag=None):
        name = "ckpt-%08d%s.armor" % (int(step),
                                      ("-" + tag) if tag else "")
        return os.path.join(self.directory, name)

    def save(self, step, tag=None):
        """One snapshot now.  Returns the path written."""
        from ..telemetry import metrics as _tmetrics
        t0 = time.perf_counter()
        state = snapshot_trainer(self.trainer, step)
        path = self._path(step, tag=tag)
        manifest = save_state(path, state)
        _tmetrics.checkpoint_saved(time.perf_counter() - t0,
                                   manifest["nbytes"], int(step))
        self.last_step = int(step)
        if tag is None:
            self._prune()
        return path

    def step_end(self, step):
        """Training-loop hook: save when the period divides ``step``.
        With no period configured this is a two-attribute no-op."""
        self.last_step = int(step)
        if self.every and step > 0 and step % self.every == 0:
            return self.save(step)
        return None

    def _prune(self):
        snaps = self._scan()
        for step, path in snaps[:-self.keep]:
            for p in (path, path + ".manifest.json"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _scan(self):
        """[(step, path)] of periodic snapshots, oldest first (emergency
        ones — tagged filenames — sort by their step too)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = re.match(r"ckpt-(\d+)(?:-[\w.-]+)?\.armor$", name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    # -- resuming -----------------------------------------------------------
    def latest_valid(self):
        """(step, path, state) of the newest snapshot that passes
        validation, or None.  Corrupt/truncated snapshots are skipped
        (recorded in the ring) — the resume contract is the last VALID
        state, not the last write attempt."""
        from ..telemetry import blackbox as _blackbox
        for step, path in reversed(self._scan()):
            try:
                return step, path, load_state(path)
            except CheckpointCorruptError as exc:
                _blackbox.record("checkpoint_skipped", path=path,
                                 reason=str(exc))
        return None

    def resume(self, data_iter=None):
        """Restore the newest valid snapshot onto the trainer.  Returns
        the restored step (0 when there is nothing to resume).  With a
        ``data_iter`` the iterator is fast-forwarded by that many
        batches so the next batch is the one the dead run would have
        consumed."""
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import metrics as _tmetrics
        found = self.latest_valid()
        if found is None:
            return 0
        step, path, state = found
        restore_trainer(self.trainer, state)
        self.last_step = step
        if data_iter is not None:
            fast_forward(data_iter, step)
        _blackbox.record("checkpoint_restored", path=path, step=step)
        _tmetrics.checkpoint_restored(step)
        return step
