"""graftarmor deterministic fault injection.

``GRAFT_FAULTS`` threads named chaos into the REAL code paths — the PS
RPC wire, collective issue/wait, the DataLoader worker, the serving
dispatcher — so the recovery machinery is exercised by the same calls
production takes, not by mocks.  Injection is deterministic: which
arrival at a site fires is decided by counters and a seeded PRNG, never
by wall clock, so a chaos run replays bit-identically.

Spec grammar (documented in docs/robustness.md)::

    GRAFT_FAULTS = clause (";" clause)*
    clause      = site ":" kind (":" key "=" value)*
    site        = dotted site name; trailing "*" is a prefix wildcard
    kind        = drop | delay | error | disconnect | kill

Selector keys (all optional):

* ``n=K``     — fire on the K-th arrival at the site (1-based), once.
* ``every=K`` — fire on every K-th arrival.
* ``p=F``     — fire each arrival with probability F (seeded PRNG).
* ``times=N`` — cap total fires (default 1 for ``n=``, unlimited
  otherwise).
* ``ms=N``    — duration for ``kind=delay`` (default 50).
* ``seed=S``  — PRNG seed for ``p=`` (default 0; folded with the site
  name so two probabilistic clauses never share a stream).
* ``rank=R``  — only fire on worker rank R (see :func:`set_rank`).
* any other ``key=value`` must match the keyword context the site
  passes to :func:`fault_point` (e.g. ``cmd=push`` on the PS wire).

Kind semantics are generic where possible: ``delay`` sleeps ``ms``
milliseconds inside :func:`fault_point`; ``error`` raises
:class:`~.errors.FaultInjectedError`; ``kill`` is ``os._exit(137)`` —
the kill-rank-mid-step harness for multi-process tests.  ``drop`` and
``disconnect`` are returned as strings for the site to interpret (the
PS wire turns them into a swallowed send / a closed socket, exercising
its timeout and reconnect paths); a site that receives a kind it cannot
express ignores it.

Every fired fault lands in the flight recorder as a ``fault_injected``
event and bumps ``graft_faults_injected_total{site,kind}``, so a chaos
post-mortem can separate injected failures from real ones.  With
``GRAFT_FAULTS`` unset the whole module is a near-no-op: one environment
lookup against a memoized raw string per call.
"""
from __future__ import annotations

import os
import random
import threading
import time
import zlib

from .errors import FaultInjectedError

__all__ = ["fault_point", "configure", "reset", "active_rules",
           "set_rank", "KINDS"]

KINDS = ("drop", "delay", "error", "disconnect", "kill")

_SELECTOR_KEYS = ("n", "every", "p", "times", "ms", "seed", "rank")

_lock = threading.Lock()
_raw = [None]           # the GRAFT_FAULTS string the rules were built from
_rules = []             # parsed _Rule list (empty = injection disabled)
_rank = [None]          # worker rank for rank= filters (set_rank)


class _Rule(object):
    __slots__ = ("site", "prefix", "kind", "n", "every", "p", "times",
                 "ms", "match", "rank", "rng", "arrivals", "fires")

    def __init__(self, site, kind, opts):
        self.prefix = site.endswith("*")
        self.site = site[:-1] if self.prefix else site
        self.kind = kind
        self.n = int(opts["n"]) if "n" in opts else None
        self.every = int(opts["every"]) if "every" in opts else None
        self.p = float(opts["p"]) if "p" in opts else None
        default_times = 1 if (self.n is not None
                              and self.every is None
                              and self.p is None) else None
        self.times = int(opts["times"]) if "times" in opts else default_times
        self.ms = float(opts.get("ms", 50.0))
        self.rank = int(opts["rank"]) if "rank" in opts else None
        seed = int(opts.get("seed", 0))
        self.rng = random.Random(seed ^ zlib.crc32(site.encode()))
        self.match = {k: v for k, v in opts.items()
                      if k not in _SELECTOR_KEYS}
        self.arrivals = 0
        self.fires = 0

    def wants(self, site, ctx):
        if self.prefix:
            if not site.startswith(self.site):
                return False
        elif site != self.site:
            return False
        if self.rank is not None and self.rank != _rank[0]:
            return False
        for k, v in self.match.items():
            if str(ctx.get(k)) != v:
                return False
        return True

    def decide(self):
        """One arrival reached a matching rule: fire?  Counter- and
        PRNG-driven only — replays are deterministic."""
        self.arrivals += 1
        if self.times is not None and self.fires >= self.times:
            return False
        if self.n is not None and self.arrivals == self.n:
            self.fires += 1
            return True
        if self.every is not None and self.arrivals % self.every == 0:
            self.fires += 1
            return True
        if self.p is not None and self.rng.random() < self.p:
            self.fires += 1
            return True
        if self.n is None and self.every is None and self.p is None:
            self.fires += 1     # bare clause: every matching arrival
            return True
        return False


def _parse(raw):
    rules = []
    for clause in (raw or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) < 2:
            raise ValueError("GRAFT_FAULTS clause %r: want site:kind[:k=v...]"
                             % clause)
        site, kind = parts[0].strip(), parts[1].strip().lower()
        if kind not in KINDS:
            raise ValueError("GRAFT_FAULTS clause %r: unknown kind %r "
                             "(want one of %s)" % (clause, kind, list(KINDS)))
        opts = {}
        for kv in parts[2:]:
            if "=" not in kv:
                raise ValueError("GRAFT_FAULTS clause %r: bad option %r"
                                 % (clause, kv))
            k, v = kv.split("=", 1)
            opts[k.strip()] = v.strip()
        rules.append(_Rule(site, kind, opts))
    return rules


def configure(spec):
    """Install a fault spec programmatically (tests/selftest).  Passing
    None/"" clears every rule.  Counters reset — a fresh configure is a
    fresh deterministic replay.  The env var is updated to match: the
    hot path memoizes on the raw GRAFT_FAULTS string, so a programmatic
    spec that left the env untouched would be clobbered by the next
    :func:`fault_point`'s staleness check."""
    with _lock:
        if spec:
            os.environ["GRAFT_FAULTS"] = spec
        else:
            os.environ.pop("GRAFT_FAULTS", None)
        _raw[0] = os.environ.get("GRAFT_FAULTS")
        _rules[:] = _parse(_raw[0])
    return list(_rules)


def reset():
    """Drop all rules (clears GRAFT_FAULTS — see :func:`configure`)."""
    with _lock:
        os.environ.pop("GRAFT_FAULTS", None)
        _raw[0] = None
        _rules[:] = []


def active_rules():
    """The live rule list (selftest/debug introspection)."""
    _refresh()
    return list(_rules)


def set_rank(r):
    """Stamp this process's worker rank for ``rank=`` clause filters
    (DistKVStore calls it next to blackbox.set_rank)."""
    _rank[0] = None if r is None else int(r)


def _refresh():
    raw = os.environ.get("GRAFT_FAULTS")
    if raw != _raw[0]:
        with _lock:
            if raw != _raw[0]:      # double-checked: one thread parses
                _rules[:] = _parse(raw)
                _raw[0] = raw


def _record(site, kind, rule, ctx):
    from ..telemetry import blackbox as _blackbox
    from ..telemetry import metrics as _tmetrics
    fields = {k: v for k, v in ctx.items()
              if isinstance(v, (str, int, float, bool, type(None)))}
    fields.pop("site", None)
    _blackbox.record("fault_injected", site=site, fault=kind,
                     arrival=rule.arrivals, fire=rule.fires, **fields)
    _tmetrics.fault_injected(site, kind)


def fault_point(site, **ctx):
    """One named chaos site.  Returns None (the overwhelmingly common
    case — no spec, or no matching rule fired) or the fault kind the
    CALLER must act out (``"drop"``/``"disconnect"``); ``delay`` sleeps
    here, ``error`` raises :class:`FaultInjectedError` here, ``kill``
    exits the process here.  Disabled cost is one env lookup against a
    memoized string."""
    raw = os.environ.get("GRAFT_FAULTS")
    if raw != _raw[0]:
        _refresh()
    if not _rules:
        return None
    with _lock:
        fired = None
        for rule in _rules:
            if rule.wants(site, ctx) and rule.decide():
                fired = rule
                break
    if fired is None:
        return None
    _record(site, fired.kind, fired, ctx)
    if fired.kind == "delay":
        time.sleep(fired.ms / 1000.0)
        return None
    if fired.kind == "error":
        raise FaultInjectedError(site, detail=ctx.get("cmd"))
    if fired.kind == "kill":
        # the kill-rank-mid-step harness: flush the flight recorder's
        # evidence, then die the way a preempted host dies — no cleanup
        import sys
        sys.stderr.write("graftarmor: injected kill at %r (rank=%r)\n"
                         % (site, _rank[0]))
        sys.stderr.flush()
        os._exit(137)
    return fired.kind        # drop / disconnect: the site acts it out
