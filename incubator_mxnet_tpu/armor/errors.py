"""graftarmor typed failure taxonomy.

Every failure the armor subsystem can surface is a *typed* exception
carrying the evidence a supervisor needs to act: which RPC command gave
up after how many attempts, which collective timed out against which
dead ranks, which checkpoint failed its manifest.  Catching
:class:`ArmorError` catches all of them; nothing here imports anything,
so any layer (the watchdog thread included) can raise these without
circular-import risk.
"""

__all__ = ["ArmorError", "FaultInjectedError", "PSUnavailableError",
           "CollectiveTimeoutError", "CheckpointCorruptError",
           "ShardOwnershipError", "MembershipChangedError",
           "QuiesceTimeoutError"]


class ArmorError(RuntimeError):
    """Base of every typed robustness failure."""


class FaultInjectedError(ArmorError):
    """An injected ``kind=error`` fault (armor/faults.py) — chaos, not a
    real failure; the site name travels in ``.site`` so post-mortems can
    tell the two apart without parsing messages."""

    def __init__(self, site, detail=None):
        super().__init__("injected fault at %r%s"
                         % (site, (" (%s)" % detail) if detail else ""))
        self.site = site


class PSUnavailableError(ArmorError):
    """A parameter-service RPC exhausted its retry budget.  ``cmd`` is
    the RPC verb, ``attempts`` how many tries were burned, ``dead_ranks``
    whatever the heartbeat table knew when we gave up (may be empty —
    the server itself being gone reports no table at all)."""

    def __init__(self, cmd, attempts, last_error=None, dead_ranks=()):
        msg = ("parameter service unavailable: %r failed after %d "
               "attempt%s" % (cmd, attempts, "" if attempts == 1 else "s"))
        if dead_ranks:
            msg += "; dead ranks: %s" % list(dead_ranks)
        if last_error is not None:
            msg += " (last error: %r)" % (last_error,)
        super().__init__(msg)
        self.cmd = cmd
        self.attempts = attempts
        self.last_error = last_error
        self.dead_ranks = tuple(dead_ranks)


class CollectiveTimeoutError(ArmorError):
    """A collective/RPC bracket outlived the watchdog timeout and
    GRAFT_WATCHDOG_ESCALATE asked for a raise instead of a hang.  Names
    the stuck site, its age, and the dead ranks the heartbeat table
    reported — the fail-fast alternative to waiting for SIGKILL."""

    def __init__(self, site, age_s, timeout_s, dead_ranks=(), detail=None):
        msg = ("collective %r stuck for %.1fs (watchdog timeout %.1fs)"
               % (site, age_s, timeout_s))
        if dead_ranks:
            msg += "; dead ranks: %s" % list(dead_ranks)
        if detail:
            msg += "; detail: %r" % (detail,)
        super().__init__(msg)
        self.site = site
        self.age_s = age_s
        self.timeout_s = timeout_s
        self.dead_ranks = tuple(dead_ranks)
        self.detail = detail


class CheckpointCorruptError(ArmorError):
    """A snapshot failed structural validation or its manifest hash —
    the loader refuses to resume from it (resume falls back to the
    previous snapshot; model.resume_from_checkpoint skips the epoch)."""

    def __init__(self, path, reason):
        super().__init__("checkpoint %s is not loadable: %s" % (path, reason))
        self.path = str(path)
        self.reason = reason


class ShardOwnershipError(ArmorError):
    """A snapshot's ZeRO-1 shard layout does not match the resuming
    trainer's: a sharded snapshot landing on an unsharded trainer, an
    unsharded snapshot landing on a sharded one, or two sharded runs
    with different shard counts/axes.  Optimizer state is partitioned
    by bucket ownership, so silently restoring across layouts would
    leave most shards untrained; the saved and current specs travel in
    ``.saved`` / ``.current`` for supervisors to reconcile.  When the
    mismatch crosses a graftelastic membership epoch, ``.epoch`` names
    the snapshot's epoch (restore across a changed world size is only
    legal with GRAFT_ELASTIC=1, which re-partitions deterministically
    instead of raising this)."""

    def __init__(self, saved, current, epoch=None):
        def _fmt(spec):
            if not spec:
                return "unsharded"
            return "%s-sharded n=%s" % (spec.get("axis"), spec.get("n"))
        msg = ("shard layout mismatch: snapshot is %s but this trainer is "
               "%s — re-launch with the snapshot's GRAFT_SHARD_OPTIMIZER "
               "topology (or retrain)" % (_fmt(saved), _fmt(current)))
        if epoch is not None:
            msg += ("; snapshot was taken at membership epoch %d — set "
                    "GRAFT_ELASTIC=1 to re-partition shard state across "
                    "the epoch boundary" % int(epoch))
        super().__init__(msg)
        self.saved = dict(saved) if saved else None
        self.current = dict(current) if current else None
        self.epoch = None if epoch is None else int(epoch)


class MembershipChangedError(ArmorError):
    """The cluster membership moved under a caller (graftelastic): a
    collective, rejoin stream, or barrier observed a membership epoch
    other than its own — the world it was issued against no longer
    exists.  Carries both epochs plus the departed/joined rank sets so
    a supervisor can quiesce, re-partition, and retry at the new epoch
    instead of mispairing the wire."""

    def __init__(self, old_epoch, new_epoch, departed=(), joined=(),
                 detail=None):
        msg = ("membership changed: epoch %d -> %d"
               % (int(old_epoch), int(new_epoch)))
        if departed:
            msg += "; departed ranks: %s" % sorted(departed)
        if joined:
            msg += "; joined ranks: %s" % sorted(joined)
        if detail:
            msg += " (%s)" % (detail,)
        super().__init__(msg)
        self.old_epoch = int(old_epoch)
        self.new_epoch = int(new_epoch)
        self.departed = tuple(sorted(departed))
        self.joined = tuple(sorted(joined))
        self.detail = detail


class QuiesceTimeoutError(CollectiveTimeoutError):
    """``DistKVStore.quiesce()`` could not drain the in-flight async
    pushes/pulls within its budget — the duplex wire is stuck (dead
    server, hung RPC), so a re-partition that remapped key ranges now
    would race the stale traffic.  A :class:`CollectiveTimeoutError`
    subtype: the same supervisors that handle watchdog escalation
    handle this."""

    def __init__(self, site, age_s, timeout_s, pending=0, dead_ranks=()):
        super().__init__(site, age_s, timeout_s, dead_ranks=dead_ranks,
                         detail="%d in-flight operation%s undrained"
                         % (pending, "" if pending == 1 else "s"))
        self.pending = int(pending)
