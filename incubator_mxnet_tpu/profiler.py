"""Profiler: chrome://tracing dump + per-op aggregate statistics.

TPU-native rebirth of src/profiler/profiler.h:256 (Profiler singleton,
ProfileDomain/Task/Event/Frame/Counter/Marker object model, chrome-trace
JSON writer at profiler.h:87,437) and python/mxnet/profiler.py
(set_config:28, set_state:79, dump:105, custom objects :151+).

Design differences, by design:

* The reference times each op on the engine worker thread
  (ProfileOperator wrapped in ExecuteOprBlock, threaded_engine.h:339).
  Here ops dispatch asynchronously into XLA, so per-op events record the
  *dispatch* span, and an optional ``sync=True`` config blocks each op
  until ready to capture true device latency (the NaiveEngine-style
  bisection mode).
* ``set_config(xprof_dir=...)`` additionally starts ``jax.profiler`` so
  the XLA-level trace (fusion boundaries, HBM traffic) lands in
  TensorBoard/XProf — the TPU-native counterpart of the VTune bridge
  (src/profiler/vtune.cc).
* Aggregate stats (aggregate_stats.cc, MXAggregateProfileStatsPrint)
  come from the same event stream via :func:`dumps`.
"""
from __future__ import annotations

import json
import threading
import time

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Domain", "Task", "Frame", "Event", "Counter", "Marker"]

_lock = threading.Lock()


class _ProfilerState:
    """Process-wide profiler singleton (ref: profiler.h Profiler::Get)."""

    def __init__(self):
        self.running = False
        self.paused = False
        self.filename = "profile.json"
        self.profile_imperative = True
        self.profile_symbolic = True
        self.profile_memory = False  # reference default: opt-in (docs/faq/env_var.md profile options)
        self.profile_api = True
        self.aggregate_stats = False
        self.sync = False
        self.xprof_dir = None
        self.events = []            # chrome trace event dicts
        self.continuous_dump = False

    def active(self):
        return self.running and not self.paused


_P = _ProfilerState()


def set_config(**kwargs):
    """ref: profiler.py set_config / MXSetProfilerConfig.

    Recognized keys: filename, profile_all, profile_imperative,
    profile_symbolic, profile_memory, profile_api, aggregate_stats,
    continuous_dump, sync (block each op for true device latency),
    xprof_dir (also capture a jax.profiler/XProf trace).
    """
    if kwargs.pop("profile_all", False):
        _P.profile_imperative = _P.profile_symbolic = True
        _P.profile_memory = _P.profile_api = True
    for key in ("filename", "profile_imperative", "profile_symbolic",
                "profile_memory", "profile_api", "aggregate_stats",
                "continuous_dump", "sync", "xprof_dir"):
        if key in kwargs:
            setattr(_P, key, kwargs.pop(key))
    if kwargs:
        raise ValueError("unknown profiler config keys: %s" % list(kwargs))


def set_state(state="stop"):
    """ref: profiler.py set_state / MXSetProfilerState ('run'|'stop')."""
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state == "run" and not _P.running:
        _P.running = True
        _P.paused = False
        if _P.xprof_dir:
            import jax
            jax.profiler.start_trace(_P.xprof_dir)
    elif state == "stop" and _P.running:
        _P.running = False
        if _P.xprof_dir:
            import jax
            jax.profiler.stop_trace()
        if _P.continuous_dump:
            dump()


def state():
    return "run" if _P.running else "stop"


def pause():
    """ref: profiler.py pause / MXProfilePause."""
    _P.paused = True


def resume():
    """ref: profiler.py resume."""
    _P.paused = False


def _now_us():
    return time.perf_counter_ns() / 1e3


def record_event(name, begin_us, end_us, cat="operator", tid=0, args=None):
    """Append one complete ('ph: X') event; called from the dispatch hooks."""
    ev = {"name": name, "cat": cat, "ph": "X", "ts": begin_us,
          "dur": end_us - begin_us, "pid": 0, "tid": tid}
    if args:
        ev["args"] = args
    with _lock:
        _P.events.append(ev)


def append_raw_event(ev):
    """Append a pre-built chrome-trace event dict (flow events etc. from
    telemetry.tracing — the profiler stays the single event sink)."""
    with _lock:
        _P.events.append(ev)


def profile_imperative_enabled():
    return _P.profile_imperative


class _OpSpan:
    """Context manager timing one op dispatch (ProfileOperator reborn,
    threaded_engine.h:339-350).

    Under async dispatch the measured span is DISPATCH time, not device
    time — the event says so (``args.device_time``) so traces of a real
    model body cannot be misread; ``sync=True`` config blocks until
    ready inside the span and flips the flag (see invoke/Executor)."""

    __slots__ = ("name", "begin", "args")

    def __init__(self, name, args=None):
        self.name = name
        self.args = args

    def __enter__(self):
        self.begin = _now_us()
        return self

    def __exit__(self, *exc):
        # the span closes on the exception path too (marked, so a trace
        # of a crashing op is well-formed AND says the op failed)
        if exc and exc[0] is not None:
            self.args = dict(self.args or {}, error=True)
        record_event(self.name, self.begin, _now_us(), args=self.args)
        return False


def op_span(name, kind="imperative", args=None):
    """Hook used by ndarray.invoke / Executor.forward; returns a context
    manager (or None when profiling is off, keeping the hot path free)."""
    if not _P.active():
        return None
    if kind == "imperative" and not _P.profile_imperative:
        return None
    if kind == "symbolic" and not _P.profile_symbolic:
        return None
    return _OpSpan(name, args)


def want_sync():
    """Whether ops should block until ready inside the span (sync mode)."""
    return _P.active() and _P.sync


def dump(finished=True):
    """Write the chrome://tracing JSON (ref: Profiler::DumpProfile,
    profiler.h:304; python profiler.py dump:105).  Open the file at
    chrome://tracing or https://ui.perfetto.dev.

    Every dump leads with process/thread ``M`` metadata (rank-labeled
    track) and carries an ``otherData.wall_anchor`` mapping the
    profiler's monotonic clock to wall time — the identity + alignment
    data ``telemetry --analyze`` needs to merge N ranks' traces onto one
    timeline."""
    with _lock:
        events = list(_P.events)
        if finished:
            _P.events = []
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    try:
        from .telemetry import tracing as _ttracing
        meta, other = _ttracing.trace_header()
        doc["traceEvents"] = meta + events
        doc["otherData"] = other
    except Exception:
        pass                    # a dump must never fail on metadata glue
    with open(_P.filename, "w") as f:
        json.dump(doc, f)
    return _P.filename


_sampled_peak = {}   # device -> max live bytes seen by the fallback


def device_memory():
    """Per-device memory statistics — the storage-manager accounting of
    SURVEY §2.1 (ref: src/profiler/storage_profiler.h hooked at
    storage.cc:77-79; here the XLA per-device allocator IS the storage
    manager).  Primary source: ``Device.memory_stats()`` (real TPU
    runtimes report allocator counters incl. true peak).  Backends that
    report nothing (host CPU, tunneled devices) fall back to summing
    ``jax.live_arrays()`` shards per device — exact live bytes, with
    ``peak_bytes_in_use`` the max live bytes ever *sampled* by this
    function (``source`` says which accounting answered)."""
    import jax
    out = []
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        if stats:
            out.append({
                "device": str(d),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
                "num_allocs": int(stats.get("num_allocs", 0)),
                "source": "allocator",
            })
        else:
            out.append({"device": str(d), "bytes_in_use": 0,
                        "peak_bytes_in_use": 0, "bytes_limit": 0,
                        "num_allocs": 0, "source": "live_arrays"})
    fallback = {m["device"]: m for m in out if m["source"] == "live_arrays"}
    if fallback:
        # settle the pulse reaper's transient result-array refs before
        # the live-arrays walk: they are ledger bookkeeping, not
        # workload memory — counting them makes this accounting flicker
        # by reap latency.  Only the fallback path pays (briefly):
        # allocator-stats devices skip it, so a metrics scrape on a
        # busy production job never stalls here
        from .telemetry import lens as _lens
        _lens.pulse_drain(0.25)
        for arr in jax.live_arrays():
            try:
                shards = arr.addressable_shards
            except Exception:
                continue
            for sh in shards:
                m = fallback.get(str(sh.device))
                if m is not None:
                    m["bytes_in_use"] += int(sh.data.nbytes)
                    m["num_allocs"] += 1
        for dev, m in fallback.items():
            peak = max(_sampled_peak.get(dev, 0), m["bytes_in_use"])
            _sampled_peak[dev] = peak
            m["peak_bytes_in_use"] = peak
    return out


def record_memory_snapshot(name="device_memory"):
    """Append chrome-trace counter events ("C" phase) with each device's
    live bytes — storage_profiler's counter stream for the trace view."""
    if not _P.active():
        return
    ts = _now_us()
    with _lock:
        for m in device_memory():
            _P.events.append({
                "name": name, "cat": "memory", "ph": "C", "ts": ts,
                "pid": m["device"],
                "args": {"bytes_in_use": m["bytes_in_use"],
                         "peak_bytes_in_use": m["peak_bytes_in_use"]},
            })


def dumps(reset=False):
    """Aggregate per-op statistics table (ref: aggregate_stats.cc /
    MXAggregateProfileStatsPrint; python profiler.py dumps:127), plus a
    per-device memory section when ``profile_memory`` is configured."""
    with _lock:
        events = list(_P.events)
        if reset:
            _P.events = []
    stats = {}
    for ev in events:
        if "dur" not in ev:
            continue   # counter ("C") / instant ("i") events have no span
        s = stats.setdefault((ev["cat"], ev["name"]),
                             [0, 0.0, float("inf"), 0.0])
        dur = ev["dur"]
        s[0] += 1
        s[1] += dur
        s[2] = min(s[2], dur)
        s[3] = max(s[3], dur)
    lines = ["%-32s %8s %12s %12s %12s %12s"
             % ("Name", "Calls", "Total(us)", "Min(us)", "Max(us)", "Avg(us)")]
    for (cat, name), (cnt, tot, mn, mx) in sorted(
            stats.items(), key=lambda kv: -kv[1][1]):
        lines.append("%-32s %8d %12.1f %12.1f %12.1f %12.1f"
                     % (name[:32], cnt, tot, mn, mx, tot / cnt))
    if _P.profile_memory:
        lines.append("")
        lines.append("%-24s %16s %16s %16s %12s"
                     % ("Device memory", "InUse(bytes)", "Peak(bytes)",
                        "Limit(bytes)", "Allocs"))
        for m in device_memory():
            lines.append("%-24s %16d %16d %16d %12d"
                         % (m["device"][:24], m["bytes_in_use"],
                            m["peak_bytes_in_use"], m["bytes_limit"],
                            m["num_allocs"]))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Custom instrumentation objects (ref: python/mxnet/profiler.py:151-446 —
# Domain/Task/Frame/Event/Counter/Marker over the C ProfileObject model)
# ---------------------------------------------------------------------------

class Domain(object):
    """Named grouping for custom events (ref: profiler.py Domain:151)."""

    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)

    def __str__(self):
        return self.name


class _DurationObject(object):
    """start/stop pair emitting one complete event (Task/Frame/Event)."""

    _cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._begin = None

    def start(self):
        self._begin = _now_us()

    def stop(self):
        if self._begin is None:
            raise RuntimeError("%s %r stopped before start"
                               % (type(self).__name__, self.name))
        if _P.active():
            record_event(self.name, self._begin, _now_us(), cat=self._cat,
                         args={"domain": str(self.domain)}
                         if self.domain else None)
        self._begin = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def __str__(self):
        return self.name


class Task(_DurationObject):
    """ref: profiler.py Task:210."""
    _cat = "task"


class Frame(_DurationObject):
    """ref: profiler.py Frame:252 (per-iteration frames)."""
    _cat = "frame"


class Event(_DurationObject):
    """ref: profiler.py Event:294 (domain-less duration)."""
    _cat = "event"

    def __init__(self, name):
        super().__init__(None, name)


class Counter(object):
    """Monotonic user counter (ref: profiler.py Counter:330)."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self.value = value
        if _P.active():
            with _lock:
                _P.events.append({"name": self.name, "cat": "counter",
                                  "ph": "C", "ts": _now_us(), "pid": 0,
                                  "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self

    def __str__(self):
        return self.name


class Marker(object):
    """Instant event (ref: profiler.py Marker:400)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if _P.active():
            with _lock:
                _P.events.append({"name": self.name, "cat": "marker",
                                  "ph": "i", "ts": _now_us(), "pid": 0,
                                  "tid": 0,
                                  "s": {"process": "p", "global": "g",
                                        "thread": "t"}.get(scope, "p")})
