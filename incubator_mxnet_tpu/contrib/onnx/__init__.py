"""ONNX import (ref: python/mxnet/contrib/onnx/__init__.py).

``import_model(path)`` → (Symbol, arg_params, aux_params).  The
op-translation layer is self-contained; only deserializing ``.onnx``
protobuf files needs the ``onnx`` package (same dependency contract as
the reference importer).
"""
from .import_model import import_model
from .import_onnx import GraphProto
from . import op_translations
