"""ONNX operator → Symbol translations
(ref: python/mxnet/contrib/onnx/_import/op_translations.py).

Each translator: ``f(attrs: dict, inputs: list[Symbol], proto_obj) ->
Symbol``.  Covers the opset-7-era surface the reference supports for
the common CNN/MLP model families.
"""
from __future__ import annotations

from ... import symbol as sym
from ...base import MXNetError

_CONVERT = {}


def register(op_name):
    def dec(f):
        _CONVERT[op_name] = f
        return f
    return dec


def get_convert_map():
    return dict(_CONVERT)


def _pad_pair(pads):
    """ONNX [x1b, x2b, x1e, x2e] → symmetric (x1, x2); MXNet convs take
    one pad per axis."""
    if not pads:
        return None
    n = len(pads) // 2
    begin, end = pads[:n], pads[n:]
    if tuple(begin) != tuple(end):
        raise MXNetError("asymmetric ONNX pads %s not expressible as "
                         "Convolution pad; insert an explicit Pad node"
                         % (pads,))
    return tuple(begin)


@register("Conv")
def _conv(attrs, inputs, proto):
    pad = _pad_pair(attrs.get("pads"))
    kwargs = {"kernel": tuple(attrs["kernel_shape"]),
              "num_filter": proto.weight_shape(inputs[1])[0],
              "num_group": attrs.get("group", 1),
              "no_bias": len(inputs) < 3}
    if attrs.get("strides"):
        kwargs["stride"] = tuple(attrs["strides"])
    if attrs.get("dilations"):
        kwargs["dilate"] = tuple(attrs["dilations"])
    if pad:
        kwargs["pad"] = pad
    return sym.Convolution(*inputs, **kwargs)


@register("Gemm")
def _gemm(attrs, inputs, proto):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    trans_b = attrs.get("transB", 0)
    a, b = inputs[0], inputs[1]
    if attrs.get("transA", 0):
        a = sym.transpose(a, axes=(1, 0))
    if not trans_b:
        b = sym.transpose(b, axes=(1, 0))
    if alpha != 1.0:
        a = a * alpha          # alpha scales only A·B, never beta·C
    num_hidden = proto.weight_shape(inputs[1])[0 if trans_b else 1]
    args = [a, b]
    if len(inputs) > 2:
        bias = inputs[2] if beta == 1.0 else inputs[2] * beta
        args.append(bias)
    return sym.FullyConnected(*args, num_hidden=num_hidden,
                              no_bias=len(inputs) < 3)


@register("MatMul")
def _matmul(attrs, inputs, proto):
    return sym.dot(inputs[0], inputs[1])


@register("BatchNormalization")
def _batchnorm(attrs, inputs, proto):
    return sym.BatchNorm(*inputs,
                         eps=attrs.get("epsilon", 1e-5),
                         momentum=attrs.get("momentum", 0.9),
                         fix_gamma=False, use_global_stats=True)


@register("Relu")
def _relu(attrs, inputs, proto):
    return sym.Activation(inputs[0], act_type="relu")


@register("Sigmoid")
def _sigmoid(attrs, inputs, proto):
    return sym.Activation(inputs[0], act_type="sigmoid")


@register("Tanh")
def _tanh(attrs, inputs, proto):
    return sym.Activation(inputs[0], act_type="tanh")


@register("LeakyRelu")
def _leaky(attrs, inputs, proto):
    return sym.LeakyReLU(inputs[0], act_type="leaky",
                         slope=attrs.get("alpha", 0.01))


@register("Elu")
def _elu(attrs, inputs, proto):
    return sym.LeakyReLU(inputs[0], act_type="elu",
                         slope=attrs.get("alpha", 1.0))


@register("Softmax")
def _softmax(attrs, inputs, proto):
    return sym.softmax(inputs[0], axis=attrs.get("axis", 1))


@register("MaxPool")
def _maxpool(attrs, inputs, proto):
    return _pool(attrs, inputs, "max")


@register("AveragePool")
def _avgpool(attrs, inputs, proto):
    return _pool(attrs, inputs, "avg")


def _pool(attrs, inputs, kind):
    kwargs = {"kernel": tuple(attrs["kernel_shape"]), "pool_type": kind}
    if attrs.get("strides"):
        kwargs["stride"] = tuple(attrs["strides"])
    pad = _pad_pair(attrs.get("pads"))
    if pad:
        kwargs["pad"] = pad
    if kind == "avg":
        kwargs["count_include_pad"] = bool(attrs.get("count_include_pad", 0))
    return sym.Pooling(inputs[0], **kwargs)


@register("GlobalAveragePool")
def _gap(attrs, inputs, proto):
    return sym.Pooling(inputs[0], global_pool=True, pool_type="avg")


@register("GlobalMaxPool")
def _gmp(attrs, inputs, proto):
    return sym.Pooling(inputs[0], global_pool=True, pool_type="max")


@register("Add")
def _add(attrs, inputs, proto):
    return sym.broadcast_add(inputs[0], inputs[1])


@register("Sub")
def _sub(attrs, inputs, proto):
    return sym.broadcast_sub(inputs[0], inputs[1])


@register("Mul")
def _mul(attrs, inputs, proto):
    return sym.broadcast_mul(inputs[0], inputs[1])


@register("Div")
def _div(attrs, inputs, proto):
    return sym.broadcast_div(inputs[0], inputs[1])


@register("Sum")
def _sum(attrs, inputs, proto):
    out = inputs[0]
    for i in inputs[1:]:
        out = sym.broadcast_add(out, i)
    return out


@register("Concat")
def _concat(attrs, inputs, proto):
    return sym.concat(*inputs, dim=attrs.get("axis", 1))


@register("Flatten")
def _flatten(attrs, inputs, proto):
    if attrs.get("axis", 1) != 1:
        raise MXNetError("Flatten axis != 1 is not supported")
    return sym.Flatten(inputs[0])


@register("Reshape")
def _reshape(attrs, inputs, proto):
    if "shape" in attrs:              # opset-1 style attribute
        shape = tuple(attrs["shape"])
    else:                             # opset-5 style second input
        shape = tuple(int(v) for v in proto.constant_value(inputs[1]))
    return sym.reshape(inputs[0], shape=shape)


@register("Transpose")
def _transpose(attrs, inputs, proto):
    if attrs.get("perm") is not None:
        return sym.transpose(inputs[0], axes=tuple(attrs["perm"]))
    return sym.transpose(inputs[0])


@register("Dropout")
def _dropout(attrs, inputs, proto):
    return sym.Dropout(inputs[0], p=attrs.get("ratio", 0.5))


@register("Identity")
def _identity(attrs, inputs, proto):
    return inputs[0]


@register("Clip")
def _clip(attrs, inputs, proto):
    # opset-6: min/max attributes; opset-11+: min/max constant inputs
    a_min = attrs.get("min")
    a_max = attrs.get("max")
    if a_min is None and len(inputs) > 1:
        a_min = float(proto.constant_value(inputs[1]))
    if a_max is None and len(inputs) > 2:
        a_max = float(proto.constant_value(inputs[2]))
    return sym.clip(inputs[0],
                    a_min=-3.4e38 if a_min is None else a_min,
                    a_max=3.4e38 if a_max is None else a_max)


@register("Pad")
def _pad_op(attrs, inputs, proto):
    pads = attrs["pads"]
    n = len(pads) // 2
    width = []
    for i in range(n):
        width += [pads[i], pads[n + i]]
    return sym.Pad(inputs[0], mode=attrs.get("mode", "constant"),
                   pad_width=tuple(width),
                   constant_value=attrs.get("value", 0.0))


@register("Constant")
def _constant(attrs, inputs, proto):
    return proto.make_constant(attrs["value"])


@register("Exp")
def _exp(attrs, inputs, proto):
    return sym.exp(inputs[0])


@register("Log")
def _log(attrs, inputs, proto):
    return sym.log(inputs[0])


@register("Sqrt")
def _sqrt(attrs, inputs, proto):
    return sym.sqrt(inputs[0])


@register("Neg")
def _neg(attrs, inputs, proto):
    return sym.negative(inputs[0])


@register("Abs")
def _abs(attrs, inputs, proto):
    return sym.abs(inputs[0])


@register("Pow")
def _pow(attrs, inputs, proto):
    return sym.broadcast_power(inputs[0], inputs[1])


@register("ReduceMean")
def _reduce_mean(attrs, inputs, proto):
    return sym.mean(inputs[0], axis=tuple(attrs.get("axes", ())) or None,
                    keepdims=bool(attrs.get("keepdims", 1)))


@register("ReduceSum")
def _reduce_sum(attrs, inputs, proto):
    return sym.sum(inputs[0], axis=tuple(attrs.get("axes", ())) or None,
                   keepdims=bool(attrs.get("keepdims", 1)))


@register("Squeeze")
def _squeeze(attrs, inputs, proto):
    out = inputs[0]
    for ax in sorted(attrs.get("axes", ()), reverse=True):
        out = sym.squeeze(out, axis=ax)
    return out


@register("Unsqueeze")
def _unsqueeze(attrs, inputs, proto):
    out = inputs[0]
    for ax in sorted(attrs.get("axes", ())):
        out = sym.expand_dims(out, axis=ax)
    return out


@register("LRN")
def _lrn(attrs, inputs, proto):
    return sym.LRN(inputs[0], nsize=attrs.get("size", 5),
                   alpha=attrs.get("alpha", 1e-4),
                   beta=attrs.get("beta", 0.75),
                   knorm=attrs.get("bias", 1.0))
