"""User-level ONNX entry point
(ref: python/mxnet/contrib/onnx/_import/import_model.py).
"""
from __future__ import annotations

from .import_onnx import GraphProto

__all__ = ["import_model"]


def import_model(model_file):
    """Load an .onnx file → (sym, arg_params, aux_params)
    (ref: import_model.py import_model).  Requires the ``onnx`` package
    for protobuf deserialization, like the reference importer."""
    try:
        import onnx
    except ImportError:
        raise ImportError("Onnx and protobuf need to be installed. "
                          "Instructions to install - "
                          "https://github.com/onnx/onnx#installation")
    model_proto = onnx.load(model_file)
    graph = GraphProto()
    return graph.from_onnx(model_proto.graph)
