"""ONNX GraphProto → Symbol graph
(ref: python/mxnet/contrib/onnx/_import/import_onnx.py GraphProto:27).

``from_onnx`` consumes anything shaped like an ONNX graph: the real
``onnx.GraphProto`` or any object exposing ``node`` / ``input`` /
``initializer`` with the same fields — so the translation layer tests
without the onnx package installed.
"""
from __future__ import annotations

import numpy as np

from ... import ndarray as nd
from ... import symbol as sym
from ...base import MXNetError
from .op_translations import get_convert_map

__all__ = ["GraphProto"]


class GraphProto(object):
    """Stateful translator for one ONNX graph (ref: import_onnx.py:27)."""

    def __init__(self):
        self._nodes = {}       # onnx value name -> Symbol
        self._params = {}      # initializer name -> NDArray
        self._consts = {}      # value name -> numpy constant
        self.arg_dict = {}
        self.aux_dict = {}

    # hooks used by op translators -----------------------------------------
    def weight_shape(self, weight_sym):
        name = weight_sym.name
        if name in self._params:
            return tuple(self._params[name].shape)
        raise MXNetError("translator needs the shape of initializer %r"
                         % name)

    def constant_value(self, value_sym):
        name = value_sym.name
        if name in self._consts:
            return self._consts[name]
        if name in self._params:
            return self._params[name].asnumpy()
        raise MXNetError("%r is not a known constant" % name)

    def make_constant(self, array):
        """Constant node → a variable pre-filled through arg_dict."""
        name = "constant%d" % len(self._consts)
        self._consts[name] = np.asarray(array)
        self._params[name] = nd.array(np.asarray(array))
        return sym.var(name)

    # main entry ------------------------------------------------------------
    def from_onnx(self, graph):
        """Translate a graph (ref: import_onnx.py from_onnx:73).
        Returns (Symbol, arg_params, aux_params)."""
        convert_map = get_convert_map()
        for init in graph.initializer:
            # every initializer becomes a variable whether or not it is
            # also listed in graph.input (ONNX IR>=4 omits them there)
            self._params[init.name] = nd.array(self._parse_array(init))
            self._nodes[init.name] = sym.var(init.name)
        for inp in graph.input:
            name = inp if isinstance(inp, str) else inp.name
            if name not in self._nodes:
                self._nodes[name] = sym.var(name)
        for node in graph.node:
            op_type = node.op_type
            if op_type not in convert_map:
                raise MXNetError(
                    "ONNX op %r is not supported by the importer (have: %s)"
                    % (op_type, sorted(convert_map)))
            attrs = self._parse_attr(getattr(node, "attribute", []))
            inputs = [self._nodes[i] for i in node.input if i]
            out = convert_map[op_type](attrs, inputs, self)
            outputs = list(node.output)
            if len(outputs) == 1:
                self._nodes[outputs[0]] = out
            else:
                for i, oname in enumerate(outputs):
                    try:
                        self._nodes[oname] = out[i]
                    except (IndexError, TypeError):
                        break     # trailing optional outputs (e.g. BN stats)
        out_syms = [self._nodes[o if isinstance(o, str) else o.name]
                    for o in graph.output]
        final = out_syms[0] if len(out_syms) == 1 else sym.Group(out_syms)
        arg_names = set(final.list_arguments())
        aux_names = set(final.list_auxiliary_states())
        self.arg_dict = {k: v for k, v in self._params.items()
                         if k in arg_names}
        self.aux_dict = {k: v for k, v in self._params.items()
                         if k in aux_names}
        return final, self.arg_dict, self.aux_dict

    @staticmethod
    def _parse_array(tensor_proto):
        """TensorProto → numpy (ref: import_onnx.py _parse_array:146)."""
        if hasattr(tensor_proto, "asnumpy"):
            return tensor_proto.asnumpy()
        if isinstance(tensor_proto, np.ndarray):
            return tensor_proto
        try:
            from onnx import numpy_helper
            return numpy_helper.to_array(tensor_proto)
        except ImportError:
            # duck-typed initializer used by tests: .array attribute
            if hasattr(tensor_proto, "array"):
                return np.asarray(tensor_proto.array)
            raise

    @staticmethod
    def _parse_attr(attr_protos):
        """AttributeProto list (or a plain dict) → python dict
        (ref: import_onnx.py _parse_attr:155)."""
        if isinstance(attr_protos, dict):
            return dict(attr_protos)
        attrs = {}
        for a in attr_protos:
            for field in ("f", "i", "s"):
                if a.HasField(field):
                    v = getattr(a, field)
                    attrs[a.name] = v.decode() if isinstance(v, bytes) else v
            for field in ("floats", "ints", "strings"):
                if list(getattr(a, field)):
                    attrs[a.name] = tuple(getattr(a, field))
            if a.HasField("t"):
                attrs[a.name] = GraphProto._parse_array(a.t)
        return attrs
