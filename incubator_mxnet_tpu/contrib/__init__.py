"""``mx.contrib`` — experimental / contributed subsystems.

Parity: python/mxnet/contrib/__init__.py (quantization, onnx, text, ...).
"""
from . import quantization  # noqa: F401
