"""``mx.contrib`` — experimental / contributed subsystems.

Parity: python/mxnet/contrib/__init__.py (quantization, onnx, text, ...).
"""
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
