"""Indexed vocabulary (ref: python/mxnet/contrib/text/vocab.py:30)."""
from __future__ import annotations

import collections

__all__ = ["Vocabulary"]


class Vocabulary(object):
    """Token ↔ index mapping built from a frequency counter
    (ref: vocab.py Vocabulary:30).  Index 0 is the unknown token;
    ``reserved_tokens`` follow, then tokens by descending frequency."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens) or \
                unknown_token in reserved_tokens:
            raise ValueError("reserved_tokens must be unique and exclude "
                             "the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        assert isinstance(counter, collections.Counter)
        # most_freq_count caps counter-derived tokens only; unknown and
        # reserved tokens ride free (reference vocab.py semantics)
        budget = most_freq_count
        for token, freq in sorted(counter.items(),
                                  key=lambda kv: (-kv[1], kv[0])):
            if freq < min_freq or (budget is not None and budget <= 0):
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                if budget is not None:
                    budget -= 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """ref: vocab.py to_indices:160."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        """ref: vocab.py to_tokens:186."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range" % i)
            out.append(self._idx_to_token[i])
        return out[0] if single else out
