"""Token embeddings (ref: python/mxnet/contrib/text/embedding.py).

``_TokenEmbedding`` extends Vocabulary with an (n_tokens, dim) vector
table; ``CustomEmbedding`` loads word2vec/GloVe-style text files.  The
reference's GloVe/FastText classes download pretrained archives — no
egress here, so they resolve strictly from ``MXTPU_HOME`` caches
(same file formats).
"""
from __future__ import annotations

import io
import logging
import os

import numpy as np

from ... import ndarray as nd
from ... import config as _config
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "GloVe", "FastText"]

_REG = {}


def register(cls):
    """ref: embedding.py register."""
    _REG[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    """ref: embedding.py create."""
    try:
        return _REG[embedding_name.lower()](**kwargs)
    except KeyError:
        raise KeyError("unknown embedding %r (have %s)"
                       % (embedding_name, sorted(_REG)))


def get_pretrained_file_names(embedding_name=None):
    """ref: embedding.py get_pretrained_file_names — known archive names."""
    table = {
        "glove": ["glove.42B.300d.txt", "glove.6B.50d.txt",
                  "glove.6B.100d.txt", "glove.6B.200d.txt",
                  "glove.6B.300d.txt", "glove.840B.300d.txt"],
        "fasttext": ["wiki.simple.vec", "wiki.en.vec"],
    }
    if embedding_name is None:
        return table
    return table[embedding_name.lower()]


class TokenEmbedding(Vocabulary):
    """Vocabulary + vector table (ref: embedding.py _TokenEmbedding:132)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding(self, path, elem_delim=" ",
                        init_unknown_vec=None):
        """Parse a word2vec/GloVe text table (ref: embedding.py
        _load_embedding)."""
        if not os.path.isfile(path):
            raise IOError("embedding file %s not found (no egress: place "
                          "pretrained files under %s)"
                          % (path, _config.data_home()))
        tokens, vectors = [], []
        with io.open(path, "r", encoding="utf8") as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue       # word2vec header "count dim"
                token, elems = parts[0], parts[1:]
                if len(elems) <= 1:
                    logging.warning("line %d in %s: token %r with invalid "
                                    "embedding, skipped", lineno, path, token)
                    continue
                if self._vec_len == 0:
                    self._vec_len = len(elems)
                elif len(elems) != self._vec_len:
                    logging.warning("line %d in %s: dim %d != %d, skipped",
                                    lineno, path, len(elems), self._vec_len)
                    continue
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                tokens.append(token)
                vectors.append(np.asarray(elems, np.float32))
        table = np.zeros((len(self._idx_to_token), self._vec_len),
                         np.float32)
        if init_unknown_vec is not None:
            table[0] = init_unknown_vec(self._vec_len)
        start = len(self._idx_to_token) - len(vectors)
        if vectors:
            table[start:] = np.stack(vectors)
        self._idx_to_vec = nd.array(table)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """ref: embedding.py get_vecs_by_tokens:365."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            idx = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), 0)) for t in toks]
        else:
            idx = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = self._idx_to_vec.asnumpy()[idx]
        return nd.array(vecs[0] if single else vecs)

    def update_token_vectors(self, tokens, new_vectors):
        """ref: embedding.py update_token_vectors:404."""
        if isinstance(tokens, str):
            tokens = [tokens]
        arr = np.array(self._idx_to_vec.asnumpy())   # writable copy
        new = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors)
        new = new.reshape(len(tokens), -1)
        for t, v in zip(tokens, new):
            if t not in self._token_to_idx:
                raise ValueError("token %r is not indexed" % t)
            arr[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(arr)


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a user file 'token v1 v2 ...' per line
    (ref: embedding.py CustomEmbedding:658)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=None, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec or np.zeros)
        if vocabulary is not None:
            self._restrict_to(vocabulary)

    def _restrict_to(self, vocabulary):
        table = np.zeros((len(vocabulary), self._vec_len), np.float32)
        full = self._idx_to_vec.asnumpy()
        for i, tok in enumerate(vocabulary.idx_to_token):
            j = self._token_to_idx.get(tok)
            if j is not None:
                table[i] = full[j]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_vec = nd.array(table)


class _CachedPretrained(TokenEmbedding):
    _dir = ""

    def __init__(self, pretrained_file_name, embedding_root=None, **kwargs):
        super().__init__(**kwargs)
        root = embedding_root or os.path.join(_config.data_home(),
                                              "embeddings", self._dir)
        self._load_embedding(os.path.join(os.path.expanduser(root),
                                          pretrained_file_name),
                             init_unknown_vec=np.zeros)


@register
class GloVe(_CachedPretrained):
    """ref: embedding.py GloVe:468 (no egress: reads cached files)."""
    _dir = "glove"

    def __init__(self, pretrained_file_name="glove.840B.300d.txt", **kwargs):
        super().__init__(pretrained_file_name, **kwargs)


@register
class FastText(_CachedPretrained):
    """ref: embedding.py FastText:558 (no egress: reads cached files)."""
    _dir = "fasttext"

    def __init__(self, pretrained_file_name="wiki.simple.vec", **kwargs):
        super().__init__(pretrained_file_name, **kwargs)
