"""Text helpers (ref: python/mxnet/contrib/text/utils.py)."""
from __future__ import annotations

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency Counter from raw text
    (ref: utils.py count_tokens_from_str)."""
    source_str = re.sub(r"(%s)+" % seq_delim, token_delim, source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = (collections.Counter() if counter_to_update is None
               else counter_to_update)
    counter.update(t for t in source_str.split(token_delim) if t)
    return counter
