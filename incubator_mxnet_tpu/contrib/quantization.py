"""INT8 model quantization: graph pass + calibration.

TPU-native rebirth of src/operator/quantization/quantize_graph_pass.cc (the
NNVM QuantizeGraph / SetCalibTableToQuantizedGraph passes) and
python/mxnet/contrib/quantization.py (collectors, KL-divergence threshold
search, quantize_model API).

Differences by design:

* The graph pass is pure Python over our Symbol graph (the graph IR is
  Python objects, not NNVM) — same rewrite: replace each quantizable node
  with its ``quantized_`` twin, feed every float input through
  ``_contrib_quantize`` (+ runtime min/max when not offline), thread
  (min, max) range entries alongside every quantized tensor, insert
  ``_contrib_requantize`` after int32-accumulating ops and
  ``_contrib_dequantize`` at the int8/float frontier.
* Entropy calibration implements the KL-divergence threshold search with
  plain numpy (the reference needs scipy.stats.entropy).
* Layer statistics are collected by binding ``sym.get_internals()`` once
  and reading named outputs — no monitor-callback detour — so collection
  runs as one jitted XLA program per batch.
"""
from __future__ import annotations

import inspect
import logging

import numpy as np

from ..base import MXNetError
from ..context import cpu, Context
from ..symbol import Symbol, var, Group
from ..symbol.symbol import _make_node, load as sym_load
from ..ops.registry import get_op
from ..ops.quantization import QUANTIZED_OP_MAP, NEED_REQUANTIZE, quantizable
from .. import ndarray
from ..ndarray import NDArray
from ..ndarray.utils import load as nd_load
from ..io import DataIter

__all__ = ["quantize_symbol", "quantize_params", "set_calib_table",
           "quantize_model", "collect_layer_output_min_max",
           "collect_layer_outputs", "get_optimal_threshold",
           "get_optimal_thresholds", "fold_batchnorm"]


def fold_batchnorm(sym, arg_params, aux_params):
    """Fold inference-mode BatchNorm into its preceding Convolution.

    ``BN(conv(x, W) + b)`` with moving statistics is an affine map per
    output channel, so for serving the pair collapses to one convolution
    with scaled weights and a shifted bias:

        s  = gamma / sqrt(moving_var + eps)
        W' = W * s          (per output channel)
        b' = (b - moving_mean) * s + beta

    Run this BEFORE :func:`quantize_model`: without it every conv's int8
    output must be dequantized to f32 just to feed a BatchNorm, and the
    dequant/requant churn eats the MXU win.  The reference reaches the
    same state through its fused MKLDNN conv-BN subgraphs
    (ref: quantize_graph_pass.cc + subgraph fusion); here it is an
    explicit graph pass because XLA has no post-hoc fusion across the
    int8 boundary.

    Only folds when the conv's sole consumer is the BatchNorm and all
    five BN inputs (and the conv weight) are plain parameter variables.
    The returned symbol is INFERENCE-ONLY (training would need the batch
    statistics back).  Returns ``(folded_sym, arg_params, aux_params)``
    — new dicts, inputs untouched.
    """
    args = dict(arg_params)
    aux = dict(aux_params)
    topo = sym._topo()
    consumers = {}
    for node in topo:
        if node.is_variable():
            continue
        for e in node._inputs:
            b = e._base()
            consumers[id(b)] = consumers.get(id(b), 0) + 1
    for r in sym._roots():
        # an output root has an external consumer: never fold it away
        consumers[id(r._base())] = consumers.get(id(r._base()), 0) + 1

    rebuilt = {}

    def look(e):
        b = e._base()
        n = rebuilt[id(b)]
        if e._out_index is not None and n._num_outputs > 1:
            return n[e._out_index]
        return n

    for node in topo:
        if node.is_variable():
            rebuilt[id(node)] = node
            continue
        if (node._op is not None and node._op.name == "BatchNorm"
                and not node._params.get("output_mean_var")):
            src = node._inputs[0]._base()
            src_idx = node._inputs[0]._out_index or 0
            # preconditions: channel-axis BN over a single-consumer conv,
            # and every folded parameter variable used NOWHERE else — a
            # shared weight would be rescaled once per fold and read by
            # convs needing different scales (review finding, round 5);
            # axis != 1 scales the wrong weight dimension
            fold = (not src.is_variable() and src._op is not None
                    and src._op.name == "Convolution"
                    and int(node._params.get("axis", 1)) == 1
                    and consumers.get(id(src), 0) == 1 and src_idx == 0
                    and all(e._base().is_variable()
                            and consumers.get(id(e._base()), 0) == 1
                            for e in list(node._inputs[1:5])
                            + list(src._inputs[1:])))
            if fold:
                wname = src._inputs[1]._base().name
                gname, bname, mname, vname = (
                    e._base().name for e in node._inputs[1:5])
                fold = (wname in args and gname in args and bname in args
                        and mname in aux and vname in aux)
            if fold:
                eps = float(node._params.get("eps", 1e-3))
                W = args[wname].asnumpy()
                gamma = (np.ones(W.shape[0], np.float32)
                         if node._params.get("fix_gamma", True)
                         else args[gname].asnumpy())
                s = gamma / np.sqrt(aux[vname].asnumpy() + eps)
                if src._params.get("no_bias", False):
                    b0 = np.zeros(W.shape[0], np.float32)
                    bias_sym = var((src._name or "conv") + "_folded_bias")
                else:
                    bias_sym = src._inputs[2]._base()
                    b0 = args[bias_sym.name].asnumpy()
                args[wname] = ndarray.array(
                    W * s.reshape((-1,) + (1,) * (W.ndim - 1)))
                args[bias_sym.name] = ndarray.array(
                    (b0 - aux[mname].asnumpy()) * s
                    + args[bname].asnumpy())
                args.pop(gname, None)
                args.pop(bname, None)
                aux.pop(mname, None)
                aux.pop(vname, None)
                new_params = dict(src._params)
                new_params["no_bias"] = False
                folded = Symbol(src._op,
                                [look(src._inputs[0]),
                                 src._inputs[1]._base(), bias_sym],
                                new_params, src._name, src._num_outputs,
                                attrs=dict(src._attr))
                rebuilt[id(src)] = folded
                rebuilt[id(node)] = folded
                continue
        rebuilt[id(node)] = Symbol(
            node._op, [look(e) for e in node._inputs], dict(node._params),
            node._name, node._num_outputs, attrs=dict(node._attr))

    new_roots = []
    for r in sym._roots():
        b = r._base()
        n = rebuilt[id(b)]
        if r._out_index is not None and n._num_outputs > 1:
            n = n[r._out_index]
        new_roots.append(n)
    out = new_roots[0] if len(new_roots) == 1 else Group(new_roots)
    return out, args, aux


def _accepted_params(op, params):
    """Filter ``params`` down to kwargs the quantized fcompute accepts."""
    sig = inspect.signature(op.fcompute)
    ok = {n for n, p in sig.parameters.items()
          if p.kind in (p.KEYWORD_ONLY, p.POSITIONAL_OR_KEYWORD)}
    if any(p.kind == inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return dict(params)
    return {k: v for k, v in params.items() if k in ok}


def quantize_symbol(sym, excluded_sym_names=(), offline_params=()):
    """Rewrite a float Symbol graph into its INT8 form.

    ref: quantize_graph_pass.cc QuantizeGraph.  Returns a new Symbol; the
    original is untouched.  ``offline_params`` are variable names whose
    quantize nodes are replaced by ``<name>_quantize`` /
    ``<name>_quantize_min`` / ``<name>_quantize_max`` variables so the
    conversion runs once ahead of time (see :func:`quantize_params`).
    """
    excluded = set(excluded_sym_names or ())
    offline = set(offline_params or ())
    sym = sym._deepcopy()

    mirror = {}       # id(orig base node) -> ("float"|"quant", node)
    qcache = {}       # (id(orig base node), out_index) -> int8 source triple

    def _entry(e):
        """Mirrored plain (float) entry for original input symbol ``e``."""
        base = e._base()
        kind, m = mirror[id(base)]
        idx = e._out_index or 0
        if kind == "quant":
            # int8 producer feeding a float consumer → dequantize frontier
            key = (id(base), idx, "deq")
            if key not in qcache:
                qcache[key] = _make_node(
                    get_op("_contrib_dequantize"), [m[0], m[1], m[2]], {},
                    name=(base._name or "node") + "_dequantize")
            return qcache[key]
        if m._num_outputs > 1:
            return m[idx]
        return m

    def _int8_triple(e):
        """(int8 data, min, max) symbols for original input ``e``."""
        base = e._base()
        kind, m = mirror[id(base)]
        idx = e._out_index or 0
        if kind == "quant":
            return m[0], m[1], m[2]
        key = (id(base), idx)
        if key in qcache:
            q = qcache[key]
            return q[0], q[1], q[2]
        src = m[idx] if m._num_outputs > 1 else m
        name = base._name or "node"
        if base.is_variable() and name in offline:
            triple = (var(name + "_quantize", dtype="int8"),
                      var(name + "_quantize_min", dtype="float32", shape=()),
                      var(name + "_quantize_max", dtype="float32", shape=()))
            qcache[key] = _OfflineTriple(triple)
            return triple
        mn = _make_node(get_op("min"), [src], {}, name=name + "_min")
        mx = _make_node(get_op("max"), [src], {}, name=name + "_max")
        q = _make_node(get_op("_contrib_quantize"), [src, mn, mx],
                       {"out_type": "int8"}, name=name + "_quantize")
        qcache[key] = q
        return q[0], q[1], q[2]

    for node in sym._topo():
        if node.is_variable():
            mirror[id(node)] = ("float", node)
            continue
        opname = node._op.name
        if (quantizable(opname, node._params)
                and (node._name or "") not in excluded):
            qop = get_op(QUANTIZED_OP_MAP[opname])
            data_ins, range_ins = [], []
            for e in node._inputs:
                d, mn, mx = _int8_triple(e)
                data_ins.append(d)
                range_ins.extend([mn, mx])
            params = _accepted_params(qop, node._params)
            if opname in ("Convolution", "FullyConnected"):
                # float op defaults no_bias=False; the quantized twin infers
                # arity from no_bias, so pin it to the actual input count
                params["no_bias"] = len(node._inputs) < 3
            qnode = _make_node(qop, data_ins + range_ins, params,
                               name="quantized_" + (node._name or opname))
            if qop.name in NEED_REQUANTIZE:
                qnode = _make_node(get_op("_contrib_requantize"),
                                   [qnode[0], qnode[1], qnode[2]], {},
                                   name="requantize_" + (node._name or opname))
            mirror[id(node)] = ("quant", qnode)
        else:
            new = _make_node(node._op, [_entry(e) for e in node._inputs],
                             dict(node._params), name=node._name)
            new._attr.update(node._attr)
            mirror[id(node)] = ("float", new)

    outs = []
    for r in sym._roots():
        base = r._base()
        kind, m = mirror[id(base)]
        if kind == "quant":
            outs.append(_make_node(
                get_op("_contrib_dequantize"), [m[0], m[1], m[2]], {},
                name=(base._name or "out") + "_dequantize"))
        else:
            outs.append(m[r._out_index] if (r._out_index is not None
                                            and m._num_outputs > 1) else m)
    return outs[0] if len(outs) == 1 else Group(outs)


class _OfflineTriple:
    """Adapter so offline-param variables index like a 3-output node."""
    def __init__(self, triple):
        self._triple = triple

    def __getitem__(self, i):
        return self._triple[i]


def quantize_params(qsym, params):
    """Pre-quantize weights/biases referenced by a quantized symbol.

    ref: contrib/quantization.py _quantize_params — every argument named
    ``<p>_quantize`` becomes the int8 conversion of float param ``p``, with
    companions ``<p>_quantize_min`` / ``<p>_quantize_max``.
    """
    out = {}
    for name in qsym.list_arguments():
        if name.endswith("_quantize"):
            original = name[:-len("_quantize")]
            param = params[original]
            val, vmin, vmax = ndarray.contrib.quantize(
                param, ndarray.min(param), ndarray.max(param),
                out_type="int8")
            out[name] = val
            out[name + "_min"] = vmin
            out[name + "_max"] = vmax
        elif name in params:
            out[name] = params[name]
    return out


def set_calib_table(qsym, th_dict):
    """Fold calibrated thresholds into requantize nodes.

    ref: quantize_graph_pass.cc SetCalibTableToQuantizedGraph +
    MXSetCalibTableToQuantizedSymbol (c_api_symbolic.cc:604).  ``th_dict``
    maps FP32 layer output names (``conv0_output``) to (min, max).
    """
    if not th_dict:
        return qsym
    qsym = qsym._deepcopy()
    for node in qsym._topo():
        if node.is_variable() or node._op.name != "_contrib_requantize":
            continue
        producer = node._inputs[0]._base()
        pname = producer._name or ""
        if not pname.startswith("quantized_"):
            continue
        key = pname[len("quantized_"):] + "_output"
        if key in th_dict:
            lo, hi = th_dict[key]
            node._params["min_calib_range"] = float(lo)
            node._params["max_calib_range"] = float(hi)
    return qsym


# ---------------------------------------------------------------------------
# Calibration data collection (ref: _LayerOutputCollector /
# _LayerOutputMinMaxCollector + _collect_layer_statistics)
# ---------------------------------------------------------------------------

def _internal_outputs_executor(sym, data_iter, ctx, arg_params, aux_params,
                               include_layer):
    internals = sym.get_internals()
    names = internals.list_outputs()
    keep = [i for i, n in enumerate(names)
            if (include_layer is None or include_layer(n))
            and not internals[i].is_variable()]
    group = Group([internals[i] for i in keep])
    shapes = dict(data_iter.provide_data + data_iter.provide_label)
    exe = group.simple_bind(ctx=ctx, grad_req="null",
                            **{k: tuple(v) for k, v in shapes.items()})
    exe.copy_params_from(arg_params, aux_params, allow_extra_params=True)
    return exe, [names[i] for i in keep]


def _iter_calib_batches(exe, data_iter, max_num_examples,
                        data_names=None, label_names=None):
    num_examples = 0
    data_iter.reset()
    for batch in data_iter:
        feed = {}
        dnames = data_names or [n for n, _ in data_iter.provide_data]
        lnames = label_names or [n for n, _ in data_iter.provide_label]
        for name, arr in zip(dnames, batch.data):
            if name in exe.arg_dict:
                feed[name] = arr
        for name, arr in zip(lnames, batch.label or []):
            if name in exe.arg_dict:
                feed[name] = arr
        exe.forward(is_train=False, **feed)
        num_examples += batch.data[0].shape[0]
        yield exe.outputs
        if max_num_examples is not None and num_examples >= max_num_examples:
            break


def collect_layer_output_min_max(sym, data_iter, ctx, arg_params, aux_params,
                                 include_layer=None, max_num_examples=None,
                                 data_names=None, label_names=None):
    """Min/max of every layer output over the calibration set
    (ref: _collect_layer_output_min_max)."""
    exe, names = _internal_outputs_executor(sym, data_iter, ctx, arg_params,
                                            aux_params, include_layer)
    th = {}
    for outputs in _iter_calib_batches(exe, data_iter, max_num_examples,
                                       data_names, label_names):
        for name, out in zip(names, outputs):
            lo = float(ndarray.min(out).asscalar())
            hi = float(ndarray.max(out).asscalar())
            if name in th:
                th[name] = (min(th[name][0], lo), max(th[name][1], hi))
            else:
                th[name] = (lo, hi)
    return th


def collect_layer_outputs(sym, data_iter, ctx, arg_params, aux_params,
                          include_layer=None, max_num_examples=None,
                          data_names=None, label_names=None):
    """Raw layer outputs for entropy calibration
    (ref: _collect_layer_outputs)."""
    exe, names = _internal_outputs_executor(sym, data_iter, ctx, arg_params,
                                            aux_params, include_layer)
    nd_dict = {n: [] for n in names}
    for outputs in _iter_calib_batches(exe, data_iter, max_num_examples,
                                       data_names, label_names):
        for name, out in zip(names, outputs):
            nd_dict[name].append(out.asnumpy())
    return nd_dict


# ---------------------------------------------------------------------------
# Entropy (KL-divergence) threshold search
# (ref: _get_optimal_threshold — TensorRT-style calibration; numpy-only)
# ---------------------------------------------------------------------------

def _smooth(hist, eps=0.0001):
    """Replace zero mass with eps, debiting non-zero bins proportionally."""
    hist = hist.astype(np.float64)
    zeros = hist == 0
    n_zero = int(zeros.sum())
    n_nonzero = hist.size - n_zero
    if n_zero == 0 or n_nonzero == 0:
        return hist
    debit = eps * n_zero / n_nonzero
    hist = hist + eps * zeros - debit * (~zeros)
    return np.maximum(hist, 1e-12)


def _kl(p, q):
    p = p / p.sum()
    q = q / q.sum()
    return float(np.sum(p * np.log(p / q)))


def get_optimal_threshold(arr, num_bins=8001, num_quantized_bins=255):
    """Search the |threshold| minimizing KL(P_fp32 || Q_int8).

    Same algorithm as the reference's _get_optimal_threshold: clip the
    histogram at each candidate threshold (folding outliers into edge
    bins), collapse it to ``num_quantized_bins`` levels, re-expand, and
    keep the candidate with minimum divergence.
    """
    if isinstance(arr, list):
        arr = np.concatenate([np.asarray(a) for a in arr], axis=None)
    arr = np.asarray(arr, np.float32).ravel()
    min_val, max_val = float(arr.min()), float(arr.max())
    th = max(abs(min_val), abs(max_val))
    if th == 0.0:
        return min_val, max_val, 0.0, 1e-6
    hist, edges = np.histogram(arr, bins=num_bins, range=(-th, th))
    zero_idx = num_bins // 2
    half_q = num_quantized_bins // 2
    best_div, best_th = None, th
    for i in range(half_q, num_bins // 2 + 1):
        lo, hi = zero_idx - i, zero_idx + i + 1
        sliced = hist[lo:hi].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        if p.sum() == 0:
            continue
        # collapse to the quantized grid, spreading each level's mass over
        # the source bins that are non-zero
        merged = p.size // num_quantized_bins
        q = np.zeros_like(sliced)
        nonzero = sliced != 0
        for j in range(num_quantized_bins):
            s = j * merged
            e = sliced.size if j == num_quantized_bins - 1 else s + merged
            mass = sliced[s:e].sum()
            n = int(nonzero[s:e].sum())
            if n:
                q[s:e][nonzero[s:e]] = mass / n
        if q.sum() == 0:
            continue
        div = _kl(_smooth(p), _smooth(q))
        if best_div is None or div < best_div:
            best_div, best_th = div, float(edges[hi])
    return min_val, max_val, best_div or 0.0, best_th


def get_optimal_thresholds(nd_dict, num_bins=8001, num_quantized_bins=255,
                           logger=None):
    """ref: _get_optimal_thresholds — per-layer KL threshold search."""
    th_dict = {}
    for name in list(nd_dict):
        _, _, div, opt_th = get_optimal_threshold(
            nd_dict.pop(name), num_bins, num_quantized_bins)
        th_dict[name] = (-opt_th, opt_th)
        if logger is not None:
            logger.info("layer=%s optimal_threshold=%f divergence=%f",
                        name, opt_th, div)
    return th_dict


# ---------------------------------------------------------------------------
# User-level API (ref: contrib/quantization.py quantize_model)
# ---------------------------------------------------------------------------

def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   calib_layer=None, logger=logging):
    """Quantize an FP32 model to INT8, optionally calibrated.

    ref: python/mxnet/contrib/quantization.py quantize_model (:401).
    calib_mode: 'none' (runtime ranges), 'naive' (min/max over the
    calibration set), or 'entropy' (KL-optimal thresholds).
    Returns (quantized symbol, quantized arg_params, aux_params).
    """
    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(arg_params, str):
        save_dict = nd_load(arg_params)
        arg_params, aux_params = {}, {}
        for k, v in save_dict.items():
            tp, name = k.split(":", 1)
            if tp == "arg":
                arg_params[name] = v
            elif tp == "aux":
                aux_params[name] = v
    ctx = ctx if ctx is not None else cpu()
    if not isinstance(ctx, Context):
        raise ValueError("quantize_model only supports a single context")
    excluded_sym_names = list(excluded_sym_names or [])

    logger.info("Quantizing symbol")
    qsym = quantize_symbol(sym, excluded_sym_names=excluded_sym_names,
                           offline_params=list(arg_params))
    logger.info("Quantizing parameters")
    qarg_params = quantize_params(qsym, arg_params)

    if calib_mode is not None and calib_mode != "none":
        if calib_data is None or not isinstance(calib_data, DataIter):
            raise ValueError("calib_data must be a DataIter when "
                             "calib_mode=%s" % calib_mode)
        if calib_layer is None:
            calib_layer = lambda name: name.endswith("_output")
        if calib_mode == "entropy":
            nd_dict = collect_layer_outputs(
                sym, calib_data, ctx, arg_params, aux_params,
                include_layer=calib_layer,
                max_num_examples=num_calib_examples,
                data_names=list(data_names), label_names=list(label_names))
            th_dict = get_optimal_thresholds(nd_dict, logger=logger)
        elif calib_mode == "naive":
            th_dict = collect_layer_output_min_max(
                sym, calib_data, ctx, arg_params, aux_params,
                include_layer=calib_layer,
                max_num_examples=num_calib_examples,
                data_names=list(data_names), label_names=list(label_names))
        else:
            raise ValueError("unknown calib_mode %s (expected none, naive or "
                             "entropy)" % calib_mode)
        logger.info("Calibrating quantized symbol")
        qsym = set_calib_table(qsym, th_dict)
    return qsym, qarg_params, aux_params
