"""graftpulse autotuner — the first closed loop over the lens signals.

Every signal graftlens ships (``data_wait`` fraction, the
``comm_hidden_ratio``, straggler lateness) was read by HUMANS until now.
This controller closes the loop (ROADMAP "lens-driven autotuning", PR 8
carry-forward), guarded and default-off (``GRAFT_AUTOTUNE``):

* **data_wait → DataLoader workers** — when a decision window's mean
  ``data_wait`` fraction exceeds ``GRAFT_AUTOTUNE_DATA_WAIT`` (default
  0.15), the registered loader's worker count doubles (capped at
  ``GRAFT_AUTOTUNE_MAX_WORKERS``, default 8) via
  ``DataLoader.set_num_workers`` — the pool grows IN PLACE and the
  epoch iterator tops its lookahead up mid-epoch, so a starved loop
  recovers without an epoch boundary.  When every starved loader is
  already at the worker cap, the controller escalates to the loader's
  prefetch lookahead instead (``DataLoader.set_prefetch_depth``,
  doubling from ``GRAFT_PREFETCH_DEPTH`` up to
  ``GRAFT_AUTOTUNE_MAX_PREFETCH``, default 8) — deeper lookahead
  absorbs per-batch build-time variance that more threads cannot.
  Both knobs share the cooldown discipline and journal their own
  decisions (``dataloader_workers`` / ``prefetch_depth``).

* **comm_hidden_ratio → GRAFT_BUCKET_BYTES** — when the window's
  hidden-comm ratio (1 - blocked/in-flight collective time) sags below
  ``GRAFT_AUTOTUNE_COMM_HIDDEN`` (default 0.5), the bucket target
  hill-climbs: first SHRINK (smaller buckets close earlier in backward
  → earlier issue → more overlap window); if a move makes the ratio
  worse, the direction flips (bigger buckets amortize per-collective
  latency better on some wires).  Bounds:
  ``GRAFT_AUTOTUNE_MIN/MAX_BUCKET_BYTES`` (256 KiB / 64 MiB).  The knob
  is the ``GRAFT_BUCKET_BYTES`` env var itself — the Trainer re-reads
  it per step and its plan signature includes the target, so the next
  step re-packs (one serial fallback step per re-plan, the documented
  plan-change rail).

* **multi-rank bucket moves are rank-0-decides** — under
  ``jax.process_count() > 1`` only rank 0's controller moves the bucket
  knob, and the move rides the dist heartbeat allreduce as one extra
  int32 slot (``parallel/dist.py propose_bucket_bytes``): every rank —
  rank 0 included — applies it via
  :func:`apply_bucket_bytes_broadcast` on the heartbeat where it lands,
  so all plans re-pack on the same step and the lockstep auditor stays
  quiet.  Non-zero ranks' tuners are observation-only for this knob.

* **serve p99 queue_wait → batcher max_batch / max_wait** — every
  ``interval`` serve-batch lens windows the SLO ring's p99
  ``queue_wait`` is compared against ``GRAFT_AUTOTUNE_SERVE_QW_MS``
  (default 5 ms): above it the registered
  :class:`~incubator_mxnet_tpu.serving.DynamicBatcher`'s max-batch
  doubles (capped at ``GRAFT_AUTOTUNE_MAX_SERVE_BATCH``, default 256),
  then its max-wait halves (floor 0.5 ms); when the p99 relaxes below a
  quarter of the bound the squeezed max-wait recovers toward its
  configured value.  Same cooldown/journaling discipline
  (``serve_max_batch`` / ``serve_max_wait_ms`` decisions).

* **straggler lateness → bucket order** — :func:`feed_straggler_table`
  accepts ``telemetry/aggregate.py``'s straggler rows (or any
  ``{"label", "lateness_s"}`` list) and feeds each named bucket's
  lateness into the owning Trainer's per-param blocked-wait EWMA
  (``_note_bucket_lateness``) — the tape-order packing tie-breaker —
  then drops the plan caches so the next plan re-packs systematically
  late buckets earlier (``_plan_order``).

Every decision is journaled as a flight-recorder ``autotune_decision``
event (signal, knob, old → new, cooldown) and mirrored to
``graft_autotune_*`` metrics, so the controller is itself observable.
Decisions are guarded by a per-knob COOLDOWN (``GRAFT_AUTOTUNE_COOLDOWN``
windows, default 2) so an adjustment's effect lands in the signals
before the next move — no oscillation on a noisy window.

Wiring: ``DataLoader``/``Trainer`` register themselves (weakly) at
construction; the controller observes finalized lens records through
``lens.add_observer``.  With ``GRAFT_AUTOTUNE`` unset/0 the observer
returns immediately and nothing else runs — bit-identical behavior.

``python -m incubator_mxnet_tpu.telemetry.autotune --selftest`` runs the
synthetic starved-DataLoader scenario (tools/run_lint.sh tier): the
controller must grow workers until the data_wait fraction drops below
the bound within a bounded number of steps.
"""
from __future__ import annotations

import os
import threading
import weakref

from . import blackbox as _blackbox
from . import lens as _lens
from . import metrics as _metrics

__all__ = ["enabled", "set_enabled", "Autotuner", "controller",
           "register_loader", "register_trainer", "register_batcher",
           "feed_straggler_table", "apply_bucket_bytes_broadcast",
           "decisions", "reset", "selftest", "main"]

_enabled_override = None

# the decision windows accumulate TRAIN-step records only: gluon.Trainer
# and Module journal under these origins.  Serving-batch and ad-hoc
# windows carry the wrong signals (no data_wait, foreign wall)
_TRAIN_ORIGINS = frozenset(("trainer", "module"))


def set_enabled(flag):
    """Force the autotuner on/off (None = defer to GRAFT_AUTOTUNE)."""
    global _enabled_override
    _enabled_override = flag


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    return os.environ.get("GRAFT_AUTOTUNE", "").strip().lower() \
        in ("1", "true", "yes", "on")


def _env_int(name, default):
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class Autotuner(object):
    """The guarded controller.  One instance is the process-wide
    singleton (:func:`controller`); tests construct their own with
    explicit knobs and install it via :func:`_install`."""

    def __init__(self, interval=None, cooldown=None, data_wait_bound=None,
                 comm_hidden_bound=None, max_workers=None,
                 min_bucket_bytes=None, max_bucket_bytes=None,
                 max_prefetch=None, serve_qw_ms=None, max_serve_batch=None):
        self.interval = interval if interval is not None \
            else _env_int("GRAFT_AUTOTUNE_INTERVAL", 8)
        self.cooldown = cooldown if cooldown is not None \
            else _env_int("GRAFT_AUTOTUNE_COOLDOWN", 2)
        self.data_wait_bound = data_wait_bound if data_wait_bound is not None \
            else _env_float("GRAFT_AUTOTUNE_DATA_WAIT", 0.15)
        self.comm_hidden_bound = comm_hidden_bound \
            if comm_hidden_bound is not None \
            else _env_float("GRAFT_AUTOTUNE_COMM_HIDDEN", 0.5)
        self.max_workers = max_workers if max_workers is not None \
            else _env_int("GRAFT_AUTOTUNE_MAX_WORKERS", 8)
        self.min_bucket_bytes = min_bucket_bytes \
            if min_bucket_bytes is not None \
            else _env_int("GRAFT_AUTOTUNE_MIN_BUCKET_BYTES", 256 << 10)
        self.max_bucket_bytes = max_bucket_bytes \
            if max_bucket_bytes is not None \
            else _env_int("GRAFT_AUTOTUNE_MAX_BUCKET_BYTES", 64 << 20)
        self.max_prefetch = max_prefetch if max_prefetch is not None \
            else _env_int("GRAFT_AUTOTUNE_MAX_PREFETCH", 8)
        self.serve_qw_bound = (serve_qw_ms if serve_qw_ms is not None
                               else _env_float("GRAFT_AUTOTUNE_SERVE_QW_MS",
                                               5.0)) / 1e3
        self.max_serve_batch = max_serve_batch \
            if max_serve_batch is not None \
            else _env_int("GRAFT_AUTOTUNE_MAX_SERVE_BATCH", 256)
        self._lock = threading.Lock()
        self._loaders = []          # weakrefs, registration order
        self._trainers = []         # weakrefs
        self._batchers = []         # weakrefs (serving knob targets)
        self._serve_seen = 0        # serve_batch windows since last eval
        self._window = []           # lens records of the open window
        self._cooldowns = {}        # knob -> windows remaining
        self._hidden_at_move = None  # hidden ratio WHEN the last bucket
        #                              move was made (climb evaluation)
        self._bucket_move_pending = False   # that move awaits one eval
        self._bucket_dir = -1       # -1 shrink first, +1 grow
        self._decisions = []

    # -- registration --------------------------------------------------------
    def attach_loader(self, loader):
        with self._lock:
            self._loaders = [r for r in self._loaders if r() is not None]
            if not any(r() is loader for r in self._loaders):
                self._loaders.append(weakref.ref(loader))

    def attach_trainer(self, trainer):
        with self._lock:
            self._trainers = [r for r in self._trainers if r() is not None]
            if not any(r() is trainer for r in self._trainers):
                self._trainers.append(weakref.ref(trainer))

    def attach_batcher(self, batcher):
        with self._lock:
            self._batchers = [r for r in self._batchers if r() is not None]
            if not any(r() is batcher for r in self._batchers):
                self._batchers.append(weakref.ref(batcher))

    def _live(self, refs):
        return [r() for r in refs if r() is not None]

    # -- the lens observer ---------------------------------------------------
    def on_step(self, rec):
        """One finalized lens record.  GRAFT_AUTOTUNE off = immediate
        return: the default path stays bit-identical."""
        if not enabled():
            return
        if rec.get("origin") == "serve_batch":
            # serving windows feed their OWN knob (max_batch/max_wait
            # from the SLO ring's p99 queue_wait) on their own cadence —
            # mixing them into the train decision window would dilute
            # data_frac while the DataLoader starves
            with self._lock:
                self._serve_seen += 1
                if self._serve_seen >= self.interval:
                    self._serve_seen = 0
                    self._tune_serving_locked()
            return
        if rec.get("origin") not in _TRAIN_ORIGINS:
            # the lens streams EVERY window — ad-hoc step_end callers —
            # and a train+serve process would fill decision windows with
            # foreign records (data_wait 0, nonzero wall), diluting
            # data_frac below the bound while the DataLoader starves.
            # Decide on train-step windows only
            return
        with self._lock:
            self._window.append(rec)
            if len(self._window) < self.interval:
                return
            window, self._window = self._window, []
            self._evaluate_locked(window)

    # -- decision logic ------------------------------------------------------
    def _evaluate_locked(self, window):
        wall = sum(r["wall_s"] for r in window)
        if wall <= 0:
            return
        for knob in list(self._cooldowns):
            self._cooldowns[knob] -= 1
            if self._cooldowns[knob] <= 0:
                del self._cooldowns[knob]
        data_frac = sum(r["components"]["data_wait"] for r in window) / wall
        _metrics.autotune_signal("data_wait_fraction", data_frac)
        inflight = sum(r["comm_inflight_s"] for r in window)
        blocked = sum(r["comm_blocked_s"] for r in window)
        hidden = None
        if inflight > 0:
            hidden = max(0.0, min(1.0, 1.0 - blocked / inflight))
            _metrics.autotune_signal("comm_hidden_ratio", hidden)
        if data_frac > self.data_wait_bound:
            # worker growth first (more parallel batch builds); when
            # every starved loader is already at the worker cap, deepen
            # its prefetch lookahead instead — more in-flight batches
            # absorb build-time variance the extra threads can't
            if not self._grow_workers(data_frac):
                self._grow_prefetch(data_frac)
        if hidden is not None:
            self._tune_bucket_bytes(hidden)

    def _grow_workers(self, data_frac):
        """Returns True when a worker-growth decision was made (or the
        knob is cooling down from one), False when no loader can grow —
        the caller then escalates to the prefetch-depth knob."""
        if "dataloader_workers" in self._cooldowns:
            return True
        # rank by the blocked-wait DELTA since this loader was last
        # considered: the window's data_wait belongs to the loader the
        # consumer actually stalled on — growing in registration order
        # would walk a fast first-registered loader to the cap while the
        # starved one waits.  Ties (no per-loader signal, e.g. synthetic
        # windows) keep registration order — sort is stable
        ranked = []
        for loader in self._live(self._loaders):
            total = float(getattr(loader, "_blocked_wait_s", 0.0))
            seen = float(getattr(loader, "_graft_autotune_wait_seen", 0.0))
            loader._graft_autotune_wait_seen = total
            ranked.append((total - seen, loader))
        ranked.sort(key=lambda pair: -pair[0])
        for _delta, loader in ranked:
            old = int(getattr(loader, "_num_workers", 0))
            new = min(self.max_workers, max(1, old * 2))
            if new <= old:
                continue        # this loader is at the cap — try the next
            try:
                loader.set_num_workers(new)
            except Exception:
                continue
            self._decide("data_wait", "dataloader_workers", old, new,
                         data_wait_fraction=round(data_frac, 4))
            return True
        return False

    def _grow_prefetch(self, data_frac):
        """Second data knob (graftstep satellite): when worker growth is
        exhausted but ``data_wait`` still exceeds the bound, double the
        starved loader's LIVE lookahead depth
        (``DataLoader.set_prefetch_depth``, capped at
        ``GRAFT_AUTOTUNE_MAX_PREFETCH``).  Deeper lookahead lets the
        existing threads run ahead of the consumer, so one slow batch no
        longer stalls the loop.  Same cooldown discipline as every knob;
        the decision is journaled to the flight recorder
        (``autotune_decision`` with knob ``prefetch_depth``)."""
        if "prefetch_depth" in self._cooldowns:
            return
        ranked = []
        for loader in self._live(self._loaders):
            if not hasattr(loader, "set_prefetch_depth"):
                continue
            total = float(getattr(loader, "_blocked_wait_s", 0.0))
            seen = float(getattr(loader, "_graft_autotune_pf_seen", 0.0))
            loader._graft_autotune_pf_seen = total
            ranked.append((total - seen, loader))
        ranked.sort(key=lambda pair: -pair[0])
        for _delta, loader in ranked:
            old = int(loader.prefetch_depth())
            new = min(self.max_prefetch, max(1, old * 2))
            if new <= old:
                continue        # at the cap — try the next loader
            try:
                loader.set_prefetch_depth(new)
            except Exception:
                continue
            self._decide("data_wait", "prefetch_depth", old, new,
                         data_wait_fraction=round(data_frac, 4))
            return

    def _tune_bucket_bytes(self, hidden):
        if "bucket_bytes" in self._cooldowns:
            return              # the last move's effect is still landing
        # hill-climb: a move that made the ratio WORSE flips direction.
        # The last BUCKET move is tracked explicitly (not via the global
        # decision log — an interleaved worker-growth decision would
        # mask it and let the climb keep walking the wrong way), and it
        # is settled at the FIRST post-cooldown window no matter where
        # the ratio sits: a move that RECOVERED the ratio above the
        # bound must clear here too, or the stale _hidden_at_move would
        # be judged against an unrelated sag many windows later and
        # flip the climb away from a setting it just validated
        if self._bucket_move_pending:
            self._bucket_move_pending = False
            if hidden < self._hidden_at_move:
                self._bucket_dir = -self._bucket_dir
        if hidden >= self.comm_hidden_bound \
                or not self._live(self._trainers):
            return
        try:
            import jax
            multi_rank = jax.process_count() > 1
            my_rank = jax.process_index() if multi_rank else 0
        except Exception:
            multi_rank, my_rank = False, 0
        if multi_rank and my_rank != 0:
            # per-rank hill-climb moves diverge the collective stream:
            # one rank shrinking while a peer holds re-packs DIFFERENT
            # bucket plans, the mispaired wire hangs, and the lockstep
            # auditor fires on a healthy job.  Under multi-rank the knob
            # is therefore rank-0-decides: non-zero ranks observe only,
            # and apply rank 0's move when the heartbeat broadcast lands
            # (:func:`apply_bucket_bytes_broadcast`)
            return
        from ..overlap import DEFAULT_BUCKET_BYTES
        try:
            cur = int(os.environ.get("GRAFT_BUCKET_BYTES",
                                     str(DEFAULT_BUCKET_BYTES)))
        except ValueError:
            cur = DEFAULT_BUCKET_BYTES
        if cur <= 0:
            return              # bucketing disabled: not ours to enable
        new = cur // 2 if self._bucket_dir < 0 else cur * 2
        new = max(self.min_bucket_bytes, min(self.max_bucket_bytes, new))
        if new == cur:
            self._bucket_dir = -self._bucket_dir    # at a bound: reflect
            new = cur // 2 if self._bucket_dir < 0 else cur * 2
            new = max(self.min_bucket_bytes,
                      min(self.max_bucket_bytes, new))
            if new == cur:
                return
        if multi_rank:
            # rank 0: PARK the move in the dist mailbox — it takes
            # effect on every rank (this one included) only when the
            # next heartbeat allreduce carries it, so all plans re-pack
            # on the same step.  The decision is journaled NOW (starting
            # the cooldown); the landing journals separately as
            # bucket_bytes_broadcast on each rank.
            try:
                from ..parallel import dist as _dist
                _dist.propose_bucket_bytes(new)
            except Exception:
                return
            self._hidden_at_move = hidden
            self._bucket_move_pending = True
            self._decide("comm_hidden", "bucket_bytes", cur, new,
                         comm_hidden_ratio=round(hidden, 4),
                         broadcast="proposed")
            return
        os.environ["GRAFT_BUCKET_BYTES"] = str(new)
        self._hidden_at_move = hidden
        self._bucket_move_pending = True
        self._decide("comm_hidden", "bucket_bytes", cur, new,
                     comm_hidden_ratio=round(hidden, 4))

    def _tune_serving_locked(self):
        """The serving knob, evaluated every ``interval`` serve-batch
        lens windows (called under ``self._lock``).  Signal: the SLO
        ring's p99 ``queue_wait`` (``slo.component_quantile``).  Above
        ``GRAFT_AUTOTUNE_SERVE_QW_MS``: grow the batcher's max_batch
        (doubling, capped at ``GRAFT_AUTOTUNE_MAX_SERVE_BATCH``); at
        the cap, halve max-wait instead (floor 0.5 ms) — a fuller batch
        drains the queue, a shorter window stops feeding it.  Below a
        quarter of the bound: relax a squeezed max-wait back toward its
        configured value (never past it).  One shared cooldown, ticked
        on this cadence so a serve-only process still cools down."""
        cd = self._cooldowns.get("serving")
        if cd is not None:
            cd -= 1
            if cd > 0:
                self._cooldowns["serving"] = cd
                return
            self._cooldowns.pop("serving", None)
        try:
            from ..serving import slo as _slo
            p99 = _slo.component_quantile("queue_wait", 0.99)
        except Exception:
            return
        if p99 is None:
            return
        _metrics.autotune_signal("serve_queue_wait_p99_s", p99)
        for b in self._live(self._batchers):
            if p99 > self.serve_qw_bound:
                old = int(b.max_batch())
                new = min(self.max_serve_batch, max(1, old * 2))
                if new > old:
                    try:
                        b.set_max_batch(new)
                    except Exception:
                        continue
                    self._decide("serve_queue_wait", "serve_max_batch",
                                 old, new, p99_s=round(p99, 6))
                    self._cooldowns["serving"] = self.cooldown
                    continue
                oldw = float(b.max_wait_ms())
                neww = max(0.5, oldw / 2.0)
                if neww < oldw:
                    try:
                        b.set_max_wait_ms(neww)
                    except Exception:
                        continue
                    self._decide("serve_queue_wait", "serve_max_wait_ms",
                                 oldw, neww, p99_s=round(p99, 6))
                    self._cooldowns["serving"] = self.cooldown
            elif p99 < self.serve_qw_bound / 4.0:
                oldw = float(b.max_wait_ms())
                base = float(b.configured_max_wait_ms())
                if oldw < base:
                    neww = min(base, oldw * 2.0)
                    try:
                        b.set_max_wait_ms(neww)
                    except Exception:
                        continue
                    self._decide("serve_queue_wait", "serve_max_wait_ms",
                                 oldw, neww, p99_s=round(p99, 6))
                    self._cooldowns["serving"] = self.cooldown

    def feed_straggler_table(self, rows):
        """Feed cross-rank straggler lateness (``aggregate.py`` rows, or
        any ``{"label": bucket label, "lateness_s": seconds}`` list)
        into the registered Trainers' bucket-order tie-breaker, then
        drop their plan caches so the next plan re-packs systematically
        late buckets earlier.  Returns the number of buckets matched."""
        lateness = {}
        for row in rows:
            label = row.get("label")
            late = row.get("lateness_s", row.get("enter_spread_s"))
            if label is None or late is None:
                continue
            lateness[label] = max(lateness.get(label, 0.0), float(late))
        if not lateness:
            return 0
        matched = 0
        with self._lock:
            trainers = self._live(self._trainers)
        for t in trainers:
            hit = False
            for cache_attr in ("_fused_plan_cache", "_duplex_plan_cache"):
                cached = getattr(t, cache_attr, None)
                if cached is None or cached[1] is None:
                    continue
                for b in cached[1][0]:
                    late = lateness.get(t._sched_label(b))
                    if late is not None:
                        t._note_bucket_lateness(b, late)
                        matched += 1
                        hit = True
            if hit:
                # force a re-pack with the fresh tie-break (one tuple-
                # compare miss next step; the serial fallback step is
                # the documented plan-change cost)
                t._fused_plan_cache = None
                t._duplex_plan_cache = None
        if matched:
            self._decide("straggler_lateness", "bucket_order",
                         "cached-plan", "re-pack",
                         buckets_matched=matched,
                         labels=sorted(lateness))
        return matched

    def _decide(self, signal, target, old, new, **extra):
        rec = dict(signal=signal, target=target, old=old, new=new,
                   cooldown_windows=self.cooldown, **extra)
        self._decisions.append(rec)
        self._cooldowns[target] = self.cooldown
        _blackbox.record("autotune_decision", **rec)
        _metrics.autotune_decision(signal, target, old,
                                   new if isinstance(new, (int, float))
                                   else 1.0)

    def decisions(self):
        return [dict(d) for d in self._decisions]


# ---------------------------------------------------------------------------
# the process-wide singleton + registration surface
# ---------------------------------------------------------------------------

_controller = [None]
_controller_lock = threading.Lock()


def controller():
    """The process-wide controller (created on first registration and
    hooked into the lens observer stream)."""
    with _controller_lock:
        if _controller[0] is None:
            _install(Autotuner())
        return _controller[0]


def _install(ctrl):
    """Swap the active controller (tests / selftest).  Call under no
    lock of ``ctrl``."""
    old = _controller[0]
    if old is not None:
        _lens.remove_observer(old.on_step)
    _controller[0] = ctrl
    if ctrl is not None:
        _lens.add_observer(ctrl.on_step)
    return old


def register_loader(loader):
    """Called by ``DataLoader.__init__``: the loader becomes a worker-
    growth target.  Weak registration — no lifetime change, ~free when
    the autotuner is off."""
    controller().attach_loader(loader)


def register_trainer(trainer):
    """Called by ``gluon.Trainer.__init__``: the trainer becomes a
    bucket-bytes / bucket-order target."""
    controller().attach_trainer(trainer)


def register_batcher(batcher):
    """Called by ``serving.DynamicBatcher.__init__``: the batcher's
    max-batch / max-wait become live serving-knob targets."""
    controller().attach_batcher(batcher)


def feed_straggler_table(rows):
    """Module-level convenience over :meth:`Autotuner.feed_straggler_table`
    (e.g. piping ``telemetry --analyze --json``'s ``stragglers`` rows
    back into a live job)."""
    return controller().feed_straggler_table(rows)


def apply_bucket_bytes_broadcast(nbytes):
    """Apply a rank-0 bucket-bytes move delivered by the dist heartbeat
    broadcast (``parallel/dist.py _heartbeat_skew``).  EVERY rank — rank
    0 included — flips ``GRAFT_BUCKET_BYTES`` here, on the heartbeat
    where the broadcast landed, so all ranks' plan signatures change on
    the same step and the collective stream stays in lockstep.  Each
    landing is journaled under target ``bucket_bytes_broadcast``
    (distinct from rank 0's proposal record).  Returns True when the
    knob moved."""
    try:
        nbytes = int(nbytes)
    except (TypeError, ValueError):
        return False
    if nbytes <= 0:
        return False
    old = os.environ.get("GRAFT_BUCKET_BYTES")
    if old is not None and old.strip() == str(nbytes):
        return False
    os.environ["GRAFT_BUCKET_BYTES"] = str(nbytes)
    _blackbox.record("autotune_decision", signal="comm_hidden",
                     target="bucket_bytes_broadcast",
                     old=old, new=nbytes)
    _metrics.autotune_decision("comm_hidden", "bucket_bytes_broadcast",
                               old or 0, nbytes)
    return True


def decisions():
    c = _controller[0]
    return c.decisions() if c is not None else []


def reset():
    """Drop the controller (tests)."""
    with _controller_lock:
        _install(None)


# ---------------------------------------------------------------------------
# selftest: the synthetic starved-DataLoader scenario (lint tier)
# ---------------------------------------------------------------------------

def selftest(max_steps=80, item_delay_s=0.005, compute_s=0.004,
             verbose=False):
    """The controller must grow the loader's workers until the data_wait
    fraction drops below the bound, within ``max_steps``.  Returns a
    list of problems — empty means pass."""
    import time as _time
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import Dataset

    class SlowDataset(Dataset):
        def __init__(self, n):
            self._n = n

        def __len__(self):
            return self._n

        def __getitem__(self, idx):
            _time.sleep(item_delay_s)       # the starved producer
            return np.full((4,), float(idx), np.float32)

    problems = []
    prev_lens = _lens._enabled_override
    _lens.set_enabled(True)
    _lens.reset()
    set_enabled(True)
    ctrl = Autotuner(interval=4, cooldown=1, data_wait_bound=0.10,
                     max_workers=4)
    old_ctrl = _install(ctrl)
    try:
        p = gluon.Parameter("at0", shape=(4,))
        p.initialize(ctx=mx.cpu())
        trainer = gluon.Trainer([p], "sgd", {"learning_rate": 0.01},
                                kvstore=mx.kv.create("local"))
        loader = DataLoader(SlowDataset(4096), batch_size=4,
                            num_workers=1, prefetch_device=False)
        ctrl.attach_loader(loader)

        steps = 0
        window_fracs = []
        it = iter(loader)
        while steps < max_steps:
            batch = next(it)
            with autograd.record():
                loss = (p.data() * batch.mean()).sum()
            loss.backward()
            _time.sleep(compute_s)          # the synthetic device step
            trainer.step(1)
            steps += 1
            recs = _lens.steps()
            if recs and steps % ctrl.interval == 0:
                w = recs[-ctrl.interval:]
                wall = sum(r["wall_s"] for r in w)
                frac = sum(r["components"]["data_wait"] for r in w) / wall
                window_fracs.append(frac)
                if verbose:
                    print("step %d workers=%d data_wait=%.2f"
                          % (steps, loader._num_workers, frac))
                grew = any(d["target"] == "dataloader_workers"
                           for d in ctrl.decisions())
                if grew and frac < ctrl.data_wait_bound:
                    break
        grows = [d for d in ctrl.decisions()
                 if d["target"] == "dataloader_workers"]
        if not grows:
            problems.append("controller never grew the starved loader's "
                            "workers (final data_wait windows: %s)"
                            % [round(f, 3) for f in window_fracs[-4:]])
        if not window_fracs or window_fracs[-1] >= ctrl.data_wait_bound:
            problems.append(
                "data_wait fraction never converged below the %.2f bound "
                "within %d steps (windows: %s, workers: %d)"
                % (ctrl.data_wait_bound, steps,
                   [round(f, 3) for f in window_fracs[-6:]],
                   loader._num_workers))
        ring = [e for e in _blackbox.events()
                if e.get("kind") == "autotune_decision"]
        if len(ring) < len(ctrl.decisions()):
            problems.append("only %d of %d decisions landed in the "
                            "flight-recorder ring"
                            % (len(ring), len(ctrl.decisions())))
        loader.close()
        return problems
    finally:
        _install(old_ctrl)
        set_enabled(None)
        _lens.set_enabled(prev_lens)
        _lens.reset()


def main(argv=None):
    import argparse
    import sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.telemetry.autotune",
        description="graftpulse autotuner selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic starved-DataLoader scenario: the "
                         "controller must converge (CI smoke tier)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.print_help()
        return 2
    problems = selftest(verbose=args.verbose)
    if problems:
        for p in problems:
            print("graftpulse autotune selftest FAIL: %s" % p,
                  file=sys.stderr)
        return 1
    print("graftpulse autotune selftest OK (starved loader converged; "
          "decisions journaled)")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
