"""Unified metrics registry: Counter / Gauge / Histogram with labels.

The process-wide telemetry spine of the framework (graftscope).  Every
subsystem that previously kept its own ad-hoc counters reports here:

* engine        — flush causes + segment-length histogram (the registry
                  absorbs ``engine.flush_stats()``: the counters ARE the
                  backing data the dict view is rebuilt from),
* kvstore       — push/pull raw bytes, wire bytes after gradient
                  compression, cumulative compression ratio,
* io            — batches delivered per iterator + batches/sec EWMA,
* autograd      — tape size at backward time (histogram) and the live
                  tape-node gauge,
* device memory — per-device in-use/peak/limit gauges (sampled from
                  ``profiler.device_memory()`` at snapshot time),
* training loop — per-phase (fwd/bwd/update/kvstore) latency histograms.

Two expositions: :meth:`MetricsRegistry.snapshot` (JSON-able dict, what
the benches embed) and :meth:`MetricsRegistry.prometheus_text` (the
Prometheus text format, round-trippable via
:func:`parse_prometheus_text`).  ``GRAFT_TELEMETRY=0`` turns every
increment into a no-op; the CLI (`python -m incubator_mxnet_tpu.telemetry`)
renders the snapshot of the default registry.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
           "enabled", "set_enabled", "parse_prometheus_text",
           "compact_snapshot"]

_enabled_override = None


def set_enabled(flag):
    """Force telemetry on/off (None = defer to GRAFT_TELEMETRY)."""
    global _enabled_override
    _enabled_override = flag


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    return os.environ.get("GRAFT_TELEMETRY", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def _label_key(labelnames, labels):
    if set(labels) != set(labelnames):
        raise ValueError("expected labels %s, got %s"
                         % (list(labelnames), sorted(labels)))
    return tuple(str(labels[n]) for n in labelnames)


class _Metric(object):
    kind = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series = {}          # label-value tuple -> sample
        self._lock = threading.Lock()

    def _sample(self, labels):
        key = _label_key(self.labelnames, labels)
        s = self._series.get(key)
        if s is None:
            with self._lock:
                s = self._series.setdefault(key, self._new_sample())
        return s

    def clear(self):
        with self._lock:
            self._series.clear()

    def labels_of(self, key):
        return dict(zip(self.labelnames, key))

    def samples(self):
        """[(labels dict, sample payload)] — payload shape is per-kind.

        The export runs UNDER the lock: a histogram payload reads
        several list slots, and exporting outside the lock let a
        concurrent ``observe`` tear the snapshot (bucket counts from
        one observation, sum from the next) — the watchdog samples from
        a background thread, so snapshots must be self-consistent."""
        with self._lock:
            return [(self.labels_of(k), self._export(s))
                    for k, s in self._series.items()]


class Counter(_Metric):
    """Monotonic counter (per label set)."""

    kind = "counter"

    def _new_sample(self):
        return [0.0]

    def inc(self, value=1, **labels):
        if not enabled():
            return
        if value < 0:
            raise ValueError("counters only go up (got %r)" % value)
        s = self._sample(labels)
        with self._lock:
            s[0] += value

    def set(self, value, **labels):
        """Collector-side absolute set (for mirroring external counters)."""
        if not enabled():
            return
        s = self._sample(labels)
        with self._lock:
            s[0] = float(value)

    def value(self, **labels):
        return self._sample(labels)[0]

    def _export(self, s):
        return s[0]


class Gauge(_Metric):
    """Point-in-time value (per label set)."""

    kind = "gauge"

    def _new_sample(self):
        return [0.0]

    def set(self, value, **labels):
        if not enabled():
            return
        s = self._sample(labels)
        with self._lock:
            s[0] = float(value)

    def inc(self, value=1, **labels):
        if not enabled():
            return
        s = self._sample(labels)
        with self._lock:
            s[0] += value

    def dec(self, value=1, **labels):
        self.inc(-value, **labels)

    def value(self, **labels):
        return self._sample(labels)[0]

    def _export(self, s):
        return s[0]


_DEFAULT_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 1.0, 10.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ≤ its upper bound; +Inf is implicit)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_sample(self):
        # [counts per bucket..., +Inf count, sum]
        return [0] * (len(self.buckets) + 1) + [0.0]

    def observe(self, value, **labels):
        if not enabled():
            return
        s = self._sample(labels)
        with self._lock:
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s[i] += 1
            s[len(self.buckets)] += 1      # +Inf
            s[-1] += value

    def _export(self, s):
        return {"buckets": {("%g" % b): s[i]
                            for i, b in enumerate(self.buckets)},
                "count": s[len(self.buckets)],
                "sum": s[-1]}


class MetricsRegistry(object):
    """Named metric store + pull-collectors + expositions."""

    def __init__(self):
        self._metrics = {}
        self._collectors = []
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if type(m) is not cls or m.labelnames != tuple(labelnames):
            raise ValueError("metric %r re-registered with a different "
                             "kind/labels" % name)
        return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=_DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def register_collector(self, fn):
        """``fn(registry)`` runs before every snapshot/exposition — the
        pull path for gauges sampled from live state (device memory,
        autograd tape size)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _collect(self):
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                pass        # a broken collector must not kill exposition

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    def reset(self, prefix=None):
        """Zero every series (or only metrics whose name starts with
        ``prefix``) — keeps registrations and collectors."""
        for m in self.metrics():
            if prefix is None or m.name.startswith(prefix):
                m.clear()

    def snapshot(self, collect=True):
        """JSON-able dict of everything the registry holds."""
        if collect:
            self._collect()
        out = {}
        for m in self.metrics():
            out[m.name] = {
                "kind": m.kind,
                "help": m.help,
                "samples": [{"labels": labels, "value": payload}
                            for labels, payload in m.samples()],
            }
        return out

    def prometheus_text(self, collect=True):
        """Prometheus text exposition format v0.0.4."""
        if collect:
            self._collect()
        lines = []
        for m in sorted(self.metrics(), key=lambda m: m.name):
            lines.append("# HELP %s %s" % (m.name, m.help or m.name))
            lines.append("# TYPE %s %s" % (m.name, m.kind))
            for labels, payload in m.samples():
                if m.kind == "histogram":
                    for le, cnt in payload["buckets"].items():
                        lines.append("%s_bucket%s %s" % (
                            m.name, _fmt_labels(labels, le=le), _fmt(cnt)))
                    lines.append("%s_bucket%s %s" % (
                        m.name, _fmt_labels(labels, le="+Inf"),
                        _fmt(payload["count"])))
                    lines.append("%s_sum%s %s" % (
                        m.name, _fmt_labels(labels), _fmt(payload["sum"])))
                    lines.append("%s_count%s %s" % (
                        m.name, _fmt_labels(labels), _fmt(payload["count"])))
                else:
                    lines.append("%s%s %s" % (m.name, _fmt_labels(labels),
                                              _fmt(payload)))
        return "\n".join(lines) + "\n"


def _fmt(v):
    f = float(v)
    return ("%d" % int(f)) if f == int(f) else repr(f)


def _fmt_labels(labels, **extra):
    items = list(labels.items()) + list(extra.items())
    if not items:
        return ""
    body = ",".join('%s="%s"' % (k, str(v).replace("\\", "\\\\")
                                 .replace('"', '\\"').replace("\n", "\\n"))
                    for k, v in items)
    return "{%s}" % body


def parse_prometheus_text(text):
    """Parse the text exposition back into
    ``{metric_name: {frozenset(label items): value}}`` — the inverse used
    by the round-trip tests (histogram series appear under their
    ``_bucket``/``_sum``/``_count`` sample names, as on the wire)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labelstr, value = rest.rsplit("}", 1)
            labels = {}
            for part in _split_labels(labelstr):
                k, v = part.split("=", 1)
                v = v.strip()
                if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                    v = v[1:-1]
                labels[k] = _unescape(v)
        else:
            name, value = line.rsplit(" ", 1)
            labels = {}
        out.setdefault(name.strip(), {})[
            frozenset(labels.items())] = float(value)
    return out


_UNESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape(v):
    """Left-to-right escape decoding — sequential str.replace passes
    corrupt values like a literal backslash followed by 'n'."""
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append(_UNESCAPES.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _split_labels(s):
    parts, buf, in_q, esc = [], [], False, False
    for ch in s:
        if esc:
            buf.append(ch)
            esc = False
        elif ch == "\\":
            buf.append(ch)
            esc = True
        elif ch == '"':
            buf.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in parts if p.strip()]


# ---------------------------------------------------------------------------
# default registry + the graft_* metric catalog (see docs/observability.md)
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()


def registry():
    """The process-wide default registry."""
    return _REGISTRY


_SEGMENT_LEN_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
_PHASE_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 30.0)


def engine_flush(cause, n_instructions):
    """Engine flush accounting (called once per executed flush)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_engine_flushes_total",
              "Bulk-segment flushes by cause",
              ("cause",)).inc(cause=cause)
    r.histogram("graft_engine_segment_length",
                "Instructions per flushed bulk segment", (),
                buckets=_SEGMENT_LEN_BUCKETS).observe(n_instructions)
    r.counter("graft_engine_deferred_ops_total",
              "Ops recorded into bulk segments").inc(n_instructions)


def reset_engine_metrics():
    """Paired with ``engine.reset_flush_stats()`` so both views agree."""
    _REGISTRY.reset(prefix="graft_engine_")


def kvstore_push(raw_bytes, wire_bytes):
    """One kvstore push: raw gradient bytes vs post-compression wire
    bytes (equal when no compressor is attached)."""
    if not enabled():
        return
    r = _REGISTRY
    pushed = r.counter("graft_kvstore_push_bytes_total",
                       "Raw bytes pushed into the kvstore")
    pushed.inc(raw_bytes)
    wire = r.counter("graft_kvstore_wire_bytes_total",
                     "Bytes on the wire after gradient compression")
    wire.inc(wire_bytes)
    if wire.value() > 0:
        r.gauge("graft_kvstore_compression_ratio",
                "Cumulative push raw/wire byte ratio").set(
            pushed.value() / wire.value())


def kvstore_pull(nbytes):
    if not enabled():
        return
    _REGISTRY.counter("graft_kvstore_pull_bytes_total",
                      "Bytes pulled out of the kvstore").inc(nbytes)


def trainer_state_shard_bytes(nbytes, n_shards):
    """graftzero ZeRO-1 gauge: optimizer-state bytes this rank holds for
    its shard (max over per-context updaters), plus the shard count —
    the acceptance gate \"per-rank state ~1/N of unsharded\" reads the
    pair straight off these."""
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("graft_trainer_state_shard_bytes",
            "Optimizer-state bytes held for this rank's ZeRO-1 shard").set(
        float(nbytes))
    r.gauge("graft_trainer_state_shards",
            "ZeRO-1 shard count (ranks/contexts owning state)").set(
        float(n_shards))


_io_rate = {}          # iterator name -> [last perf_counter, ewma rate]
_io_lock = threading.Lock()


def io_batch(iter_name):
    """One data batch delivered by an io pipeline iterator; maintains a
    batches/sec EWMA gauge per iterator class."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_io_batches_total",
              "Batches delivered by io pipeline iterators",
              ("iter",)).inc(iter=iter_name)
    now = time.perf_counter()
    with _io_lock:
        st = _io_rate.get(iter_name)
        if st is None:
            _io_rate[iter_name] = [now, 0.0]
            return
        dt = now - st[0]
        st[0] = now
        if dt <= 0:
            return
        inst = 1.0 / dt
        st[1] = inst if st[1] == 0.0 else 0.8 * st[1] + 0.2 * inst
        rate = st[1]
    r.gauge("graft_io_batches_per_sec",
            "EWMA batches/sec per io iterator",
            ("iter",)).set(rate, iter=iter_name)


def autograd_backward(tape_len):
    """Tape size at the start of a backward pass."""
    if not enabled():
        return
    _REGISTRY.histogram("graft_autograd_tape_size",
                        "Tape nodes walked per backward pass", (),
                        buckets=_SEGMENT_LEN_BUCKETS).observe(tape_len)


def phase(name, seconds):
    """One training-loop phase (fwd/bwd/update/kvstore) completion."""
    if not enabled():
        return
    _REGISTRY.histogram("graft_phase_seconds",
                        "Training-loop phase latency", ("phase",),
                        buckets=_PHASE_BUCKETS).observe(seconds, phase=name)


def _collect_device_memory(reg):
    """Snapshot-time gauges from the XLA per-device allocator (falls back
    to live_arrays accounting — see profiler.device_memory)."""
    from .. import profiler
    g = reg.gauge("graft_device_memory_bytes",
                  "Per-device memory from the storage accounting",
                  ("device", "kind"))
    for m in profiler.device_memory():
        g.set(m["bytes_in_use"], device=m["device"], kind="in_use")
        g.set(m["peak_bytes_in_use"], device=m["device"], kind="peak")
        g.set(m["bytes_limit"], device=m["device"], kind="limit")


def _collect_autograd_tape(reg):
    from .. import autograd
    reg.gauge("graft_autograd_tape_nodes",
              "Live tape nodes on the calling thread").set(
        len(autograd._st().tape))


def _collect_engine_stats(reg):
    """Mirror ``engine.flush_stats()`` so a snapshot is complete even if
    a flush path bypassed the incremental counters (defensive sync —
    values are authoritative from the engine's own dicts)."""
    from .. import engine
    stats = engine.flush_stats()
    c = reg.counter("graft_engine_flushes_total",
                    "Bulk-segment flushes by cause", ("cause",))
    for cause, n in stats["causes"].items():
        c.set(n, cause=cause)
    g = reg.gauge("graft_engine_replay_cache_size",
                  "Entries in the engine's bounded program caches "
                  "(GRAFT_REPLAY_CACHE_SIZE)", ("cache",))
    for name, n in engine.cache_sizes().items():
        g.set(n, cache=name)
    from .. import optimizer as _opt
    g.set(len(_opt._FUSED_STEP_CACHE), cache="fused_update")


_BUCKET_BYTE_BUCKETS = (4096, 65536, 262144, 1 << 20, 4 << 20, 16 << 20,
                        64 << 20)


def trainer_buckets(bucket_bytes_list, n_leftover):
    """One bucket plan build by the fused Trainer.step path: bucket count
    gauge + per-bucket payload-bytes histogram (graftfuse)."""
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("graft_trainer_bucket_count",
            "Gradient buckets in the current fused-step plan").set(
        len(bucket_bytes_list))
    r.gauge("graft_trainer_bucket_leftover_params",
            "Params the fused-step plan left on the per-param path").set(
        n_leftover)
    h = r.histogram("graft_trainer_bucket_bytes",
                    "Payload bytes per gradient bucket", (),
                    buckets=_BUCKET_BYTE_BUCKETS)
    for nb in bucket_bytes_list:
        h.observe(nb)


def trainer_overlap(n_overlapped, n_serial, exposed_s, inflight_s):
    """One overlapped ``Trainer.step`` (graftlap): how much of the bucket
    reduces' in-flight wall time was hidden under backward.

    ``exposed_s`` is the time step() actually spent blocked in
    ``ReduceHandle.wait``; ``inflight_s`` is the summed issue-to-ready
    wall time of the overlapped handles.  The ratio gauge is
    ``1 - exposed/inflight`` — 1.0 means every overlapped reduce landed
    before step() looked at it, 0.0 means nothing was hidden (the serial
    cost in a different place)."""
    if not enabled():
        return
    r = _REGISTRY
    c = r.counter("graft_trainer_overlap_buckets_total",
                  "Bucket reduces by issue mode (overlapped = put on the "
                  "wire mid-backward; serial = reduced inside step())",
                  ("mode",))
    c.inc(n_overlapped, mode="overlapped")
    c.inc(n_serial, mode="serial")
    r.histogram("graft_trainer_overlap_exposed_seconds",
                "Per-step reduce wait time NOT hidden under backward", (),
                buckets=_PHASE_BUCKETS).observe(exposed_s)
    if inflight_s > 0:
        r.gauge("graft_trainer_overlap_ratio",
                "Fraction of overlapped-reduce in-flight wall time hidden "
                "under the backward pass (last overlapped step)").set(
            max(0.0, min(1.0, 1.0 - exposed_s / inflight_s)))


def trainer_pull_overlap(n_overlapped, n_serial, exposed_s, inflight_s,
                         stale=0):
    """One round of weight pulls on the update_on_kvstore path
    (graftduplex): how much of the pull/broadcast in-flight wall time was
    hidden under the next forward (first-touch waits) and data loading.

    ``exposed_s`` is host time actually blocked in ``PullHandle.wait``;
    ``inflight_s`` the summed issue→wait-return wall time.  Mirrors
    ``trainer_overlap`` on the reduce side; the serial pull path reports
    with ``exposed == inflight`` so the two configurations stay
    comparable."""
    if not enabled():
        return
    r = _REGISTRY
    c = r.counter("graft_trainer_pull_buckets_total",
                  "Weight-pull groups by issue mode (overlapped = async "
                  "PullHandle waited at first touch; serial = pulled "
                  "synchronously inside the step)", ("mode",))
    c.inc(n_overlapped, mode="overlapped")
    c.inc(n_serial, mode="serial")
    r.histogram("graft_trainer_pull_exposed_seconds",
                "Per-round pull wait time NOT hidden under the next "
                "forward", (), buckets=_PHASE_BUCKETS).observe(exposed_s)
    if stale:
        r.counter("graft_trainer_pull_stale_total",
                  "Out arrays whose async-pulled value was dropped "
                  "because the array was overwritten between issue and "
                  "wait (abandon-and-fallback)").inc(stale)
    if inflight_s > 0:
        r.gauge("graft_trainer_pull_overlap_ratio",
                "Fraction of async weight-pull in-flight wall time hidden "
                "under data loading / the next forward (last pull-bearing "
                "round)").set(
            max(0.0, min(1.0, 1.0 - exposed_s / inflight_s)))


def trainer_fused_update(n_params):
    """One fused multi-tensor optimizer dispatch (per bucket, per
    context); latency lands on the existing ``update`` phase span."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_trainer_bucket_fused_updates_total",
              "Fused multi-tensor optimizer update dispatches").inc()
    r.counter("graft_trainer_bucket_fused_params_total",
              "Parameters updated through fused bucket dispatches").inc(
        n_params)


def trainer_compiled_step(n_params):
    """One whole-step compiled dispatch (graftstep: fwd+bwd+fused update
    as a single donated XLA program, gluon/step_compile.py)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_trainer_compiled_steps_total",
              "Whole-step compiled training dispatches").inc()
    r.counter("graft_trainer_compiled_params_total",
              "Parameters updated through whole-step compiled "
              "dispatches").inc(n_params)


def trainer_compiled_retrace():
    """One graftstep guard miss that built (or rebuilt) a compiled-step
    entry — steady-state loops must show zero of these after step 2."""
    if not enabled():
        return
    _REGISTRY.counter("graft_trainer_compiled_retraces_total",
                      "Compiled-step guard misses that re-traced").inc()


def trainer_compiled_fallback(reason):
    """One graftstep step that ran the bucketed-eager fallback instead
    of the compiled program, labeled by why."""
    if not enabled():
        return
    _REGISTRY.counter("graft_trainer_compiled_fallbacks_total",
                      "Compiled-step dispatches that fell back to the "
                      "bucketed-eager path",
                      ("reason",)).inc(reason=reason)


def step_retrace(reason):
    """One compiled-step guard miss, labeled by WHICH guard-key
    component churned (graftguard diff: input-sig / param-meta /
    optimizer-sig / …, or the structural miss reason) — the signal that
    separates 'new shape showed up once' from a retrace storm."""
    if not enabled():
        return
    _REGISTRY.counter("graft_step_retraces_total",
                      "Compiled-step guard misses by churned guard-key "
                      "component", ("reason",)).inc(reason=reason)


def step_guard_entries(n):
    """Live compiled-step guard-cache population (entries + ineligible
    markers) — a monotonically climbing gauge is the retrace-storm
    shape."""
    if not enabled():
        return
    _REGISTRY.gauge("graft_step_guard_entries",
                    "Entries held in the compiled-step guard "
                    "cache").set(n)


def step_retrace_storm():
    """One EH301 retrace-storm report (graftguard: >= 3 guard misses in
    an 8-call window with the churned component named)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_step_retrace_storms_total",
                      "EH301 retrace storms reported by the compile "
                      "auditor").inc()


# -- graftlens: per-step wall-time attribution --------------------------------


def lens_step(rec):
    """One finalized lens step window (telemetry/lens.py): per-component
    seconds histogram, last-step fraction gauges, and the hidden-comm
    ratio (1 - blocked/inflight collective time — the overlap view)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_lens_steps_total",
              "Training steps attributed by graftlens").inc()
    h = r.histogram("graft_lens_component_seconds",
                    "Per-step wall time by lens component", ("component",),
                    buckets=_PHASE_BUCKETS)
    g = r.gauge("graft_lens_component_fraction",
                "Last step's wall-time fraction by lens component",
                ("component",))
    wall = rec["wall_s"]
    for c, v in rec["components"].items():
        h.observe(v, component=c)
        g.set(v / wall if wall > 0 else 0.0, component=c)
    r.histogram("graft_lens_step_seconds",
                "Attributed step wall time (window end to end)", (),
                buckets=_PHASE_BUCKETS).observe(wall)
    if rec["comm_inflight_s"] > 0:
        r.gauge("graft_lens_comm_hidden_ratio",
                "1 - blocked/in-flight collective time of the last "
                "COMM-BEARING step (holds its value across comm-free "
                "steps; how much comm the overlap hid)").set(
            max(0.0, min(1.0, 1.0 - rec["comm_blocked_s"]
                         / rec["comm_inflight_s"])))
    dev = rec.get("device")
    if dev is not None:
        # device-time lens (PR 8 carry-forward): sync-mode flush spans /
        # serving batch dispatches book true device latency per window
        r.histogram("graft_lens_device_busy_seconds",
                    "Per-step device-busy time (profiler sync-mode "
                    "flushes + serving batch dispatches)", (),
                    buckets=_PHASE_BUCKETS).observe(dev["busy_s"])
        r.gauge("graft_lens_device_busy_fraction",
                "Last device-bearing step's device-busy fraction of "
                "wall (busy + idle == wall exactly)").set(
            dev["busy_s"] / wall if wall > 0 else 0.0)


# -- graftpulse: memory timeline + autotuner ---------------------------------


def mem_sample(site, in_use, peak):
    """One device-memory watermark sample at an attribution site
    (telemetry/lens.py ``mem_sample``: engine flush boundaries, fused/
    duplex buckets, serving batches)."""
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("graft_mem_peak_bytes",
            "Live device-bytes watermark by attribution site (window-"
            "local; the allocator's lifetime peak would tie every site)",
            ("site",)).set(peak, site=site)
    r.gauge("graft_mem_bytes_in_use",
            "Device bytes in use at the last memory-timeline sample"
            ).set(in_use)


def autotune_decision(signal, target, old, new):
    """One autotuner control decision (telemetry/autotune.py) — the
    controller is itself observable: every decision counts here and
    journals as a blackbox ``autotune_decision`` event."""
    if not enabled():
        return
    _REGISTRY.counter("graft_autotune_decisions_total",
                      "Autotuner control decisions by signal",
                      ("signal",)).inc(signal=signal)
    _REGISTRY.gauge("graft_autotune_setting",
                    "Current value of each autotuned knob",
                    ("target",)).set(float(new), target=target)


def autotune_signal(name, value):
    """The controller's view of its input signals (window means)."""
    if not enabled():
        return
    _REGISTRY.gauge("graft_autotune_signal",
                    "Autotuner input signal (window mean)",
                    ("signal",)).set(float(value), signal=name)


# -- graftxray: in-program phase attribution ---------------------------------

def xray_capture(reason, ok=True):
    """One completed graftxray capture session (telemetry/xray.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_xray_captures_total",
                      "graftxray capture sessions by trigger",
                      ("reason", "ok")).inc(
        reason=reason, ok="true" if ok else "false")


def xray_phase_seconds(phase, seconds):
    """True device seconds one xray phase spent inside the compiled
    program over the latest capture session.  The phase gauges plus
    ``unattributed`` sum EXACTLY to the captured program device span
    (the graftxray conservation contract)."""
    if not enabled():
        return
    _REGISTRY.gauge("graft_xray_phase_device_seconds",
                    "Device seconds per xray phase, latest capture "
                    "(phases + unattributed == program device span)",
                    ("phase",)).set(float(seconds), phase=phase)


# -- graftwatch: watchdog + dist liveness ------------------------------------

_SKEW_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 30.0)


def watchdog_status(n_inflight, oldest_age, progress_age):
    """One watchdog poll: liveness gauges refreshed from the background
    thread (telemetry/watchdog.py)."""
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("graft_watchdog_inflight",
            "Open flight-recorder brackets (flushes/collectives/phases)"
            ).set(n_inflight)
    r.gauge("graft_watchdog_oldest_inflight_seconds",
            "Age of the oldest open bracket").set(oldest_age)
    r.gauge("graft_watchdog_progress_age_seconds",
            "Wall-clock seconds since the last bracket completed").set(
        progress_age)


def watchdog_trip(site):
    """One declared hang (per tripped bracket site)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_watchdog_trips_total",
                      "Watchdog hang declarations", ("site",)).inc(site=site)


def dist_dead_nodes(n):
    """Workers whose heartbeats stopped (DistKVStore.num_dead_nodes)."""
    if not enabled():
        return
    _REGISTRY.gauge("graft_dist_dead_nodes",
                    "Workers whose parameter-service heartbeats stopped"
                    ).set(n)


def dist_worker_skew(seconds):
    """Per-step cross-worker arrival skew from the dist heartbeat."""
    if not enabled():
        return
    _REGISTRY.histogram("graft_dist_worker_skew_seconds",
                        "Per-step worker arrival skew (dist heartbeat)", (),
                        buckets=_SKEW_BUCKETS).observe(seconds)


def collective_slow(path):
    """One collective beyond GRAFT_STRAGGLER_FACTOR x its own EWMA."""
    if not enabled():
        return
    _REGISTRY.counter("graft_dist_slow_collectives_total",
                      "Collectives slower than the straggler threshold",
                      ("path",)).inc(path=path)


def tsan_report(code):
    """One grafttsan race report (EH2xx, analysis/tsan.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_tsan_reports_total",
                      "Happens-before race reports by diagnostic code",
                      ("code",)).inc(code=code)


def lockstep_divergence():
    """One detected SPMD lockstep divergence (analysis/lockstep.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_lockstep_divergence_total",
                      "Cross-rank collective-stream divergences detected"
                      ).inc()


# -- graftserve: production serving runtime -----------------------------------

_SERVE_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
_SERVE_LATENCY_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5,
                          1.0, 5.0)


def serve_request(model, wall_s, components):
    """One completed serving request: per-request latency + the four-way
    decomposition (queue_wait/batch_assembly/device_compute/host_io,
    serving/slo.py — the components sum EXACTLY to ``wall_s``)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_serve_requests_total",
              "Serving requests completed", ("model",)).inc(model=model)
    r.histogram("graft_serve_request_seconds",
                "End-to-end request latency (enqueue to response ready)",
                ("model",), buckets=_SERVE_LATENCY_BUCKETS).observe(
        wall_s, model=model)
    h = r.histogram("graft_serve_component_seconds",
                    "Per-request latency by SLO component", ("component",),
                    buckets=_SERVE_LATENCY_BUCKETS)
    for c, v in components.items():
        h.observe(v, component=c)


def serve_quantiles(p50_s, p99_s):
    """Rolling-window latency quantiles (serving/slo.py recomputes them
    over the request ring after every batch)."""
    if not enabled():
        return
    g = _REGISTRY.gauge("graft_serve_latency_seconds",
                        "Rolling request-latency quantiles over the last "
                        "GRAFT_SERVE_RING requests", ("quantile",))
    g.set(p50_s, quantile="p50")
    g.set(p99_s, quantile="p99")


def serve_batch(model, size, bucket):
    """One dispatched serving batch: ``size`` real requests padded to
    the ``bucket`` compiled batch signature."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_serve_batches_total",
              "Serving batches dispatched", ("model",)).inc(model=model)
    r.histogram("graft_serve_batch_size",
                "Real requests per dispatched batch", (),
                buckets=_SERVE_BATCH_BUCKETS).observe(size)
    if bucket > size:
        r.counter("graft_serve_padding_rows_total",
                  "Padding rows dispatched to reach a batch bucket").inc(
            bucket - size)


def serve_queue_depth(depth):
    """Requests currently queued across all models (set on every
    enqueue/pick)."""
    if not enabled():
        return
    _REGISTRY.gauge("graft_serve_queue_depth",
                    "Requests waiting in the dynamic batcher").set(depth)


def serve_errors(model, n=1):
    """Requests failed by a dispatch/model error."""
    if not enabled():
        return
    _REGISTRY.counter("graft_serve_errors_total",
                      "Serving requests failed", ("model",)).inc(
        n, model=model)


def serve_model_event(kind):
    """Registry lifecycle tick: ``load``/``reload``/``evict``/``swap``/
    ``unload`` (serving/registry.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_serve_model_events_total",
                      "Model registry lifecycle events (load/reload/"
                      "evict/swap/unload)", ("kind",)).inc(kind=kind)


def serve_residency(resident_bytes, resident_models, budget_bytes):
    """Registry residency snapshot after every load/evict/swap."""
    if not enabled():
        return
    r = _REGISTRY
    r.gauge("graft_serve_resident_bytes",
            "Model weight bytes resident in the serving registry").set(
        resident_bytes)
    r.gauge("graft_serve_resident_models",
            "Models with resident weights in the serving registry").set(
        resident_models)
    # always published (0 = unlimited) so an unlimited registry can't
    # inherit a stale budget value from an earlier bounded one
    r.gauge("graft_serve_memory_budget_bytes",
            "GRAFT_SERVE_MEMORY_BYTES residency budget (0 = "
            "unlimited)").set(budget_bytes)


def serve_parity_fallback(model):
    """One (model, shape, bucket) signature demoted to per-request
    dispatch because its batched output failed the bit-parity probe."""
    if not enabled():
        return
    _REGISTRY.counter("graft_serve_parity_fallbacks_total",
                      "Batch signatures demoted to per-request dispatch "
                      "by the parity probe", ("model",)).inc(model=model)


# -- graftarmor: fault injection, RPC self-healing, checkpointing -------------

_CKPT_WRITE_BUCKETS = (1e-3, 1e-2, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)


def fault_injected(site, kind):
    """One fault fired by the armor injection registry (armor/faults.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_faults_injected_total",
                      "Faults injected by GRAFT_FAULTS, by site and kind",
                      ("site", "kind")).inc(site=site, kind=kind)


def rpc_retry(cmd):
    """One retried parameter-service RPC attempt (parallel/ps.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_rpc_retries_total",
                      "Parameter-service RPC attempts retried after a "
                      "transient failure", ("cmd",)).inc(cmd=cmd)


def rpc_reconnect():
    """One PSClient socket rebuild after a disconnect."""
    if not enabled():
        return
    _REGISTRY.counter("graft_rpc_reconnects_total",
                      "PSClient reconnects after a broken connection").inc()


def rpc_gave_up(cmd):
    """One RPC that exhausted GRAFT_RPC_RETRIES and surfaced a typed
    PSUnavailableError."""
    if not enabled():
        return
    _REGISTRY.counter("graft_rpc_gave_up_total",
                      "Parameter-service RPCs that exhausted their retry "
                      "budget", ("cmd",)).inc(cmd=cmd)


def watchdog_escalation(site):
    """One typed hang exception raised into a waiting thread
    (GRAFT_WATCHDOG_ESCALATE, telemetry/watchdog.py)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_watchdog_escalations_total",
                      "Typed hang exceptions escalated into waiting "
                      "threads", ("site",)).inc(site=site)


def checkpoint_saved(seconds, nbytes, step):
    """One atomic training snapshot written (armor/checkpoint.py)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_checkpoint_saves_total",
              "Atomic training snapshots written").inc()
    r.histogram("graft_checkpoint_write_seconds",
                "Wall time of one snapshot write (drain + serialize + "
                "rename)", (), buckets=_CKPT_WRITE_BUCKETS).observe(seconds)
    r.gauge("graft_checkpoint_last_bytes",
            "Payload bytes of the last snapshot written").set(nbytes)
    r.gauge("graft_checkpoint_last_step",
            "Step counter captured by the last snapshot").set(step)


def checkpoint_restored(step):
    """One successful resume() from a snapshot."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_checkpoint_restores_total",
              "Training resumes restored from a snapshot").inc()
    r.gauge("graft_checkpoint_last_step",
            "Step counter captured by the last snapshot").set(step)


def elastic_epoch(epoch):
    """One membership-epoch transition applied (elastic/membership.py)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_elastic_epochs_total",
              "Membership-epoch transitions applied").inc()
    r.gauge("graft_elastic_epoch",
            "Current membership epoch of this rank").set(epoch)


def elastic_repartition(world_size, moved_keys=0):
    """One deterministic re-partition run (PS key ranges, shard owners,
    bucket plans rebuilt for a new world size)."""
    if not enabled():
        return
    r = _REGISTRY
    r.counter("graft_elastic_repartitions_total",
              "Deterministic re-partitions run at membership-epoch "
              "boundaries").inc()
    r.gauge("graft_elastic_world_size",
            "Live world size after the last re-partition").set(world_size)
    if moved_keys:
        r.counter("graft_elastic_moved_keys_total",
                  "PS keys whose owning server changed across "
                  "re-partitions").inc(moved_keys)


def elastic_rejoin_seconds(seconds, nbytes=0):
    """One checkpoint-streamed rejoin completed (elastic/rejoin.py)."""
    if not enabled():
        return
    r = _REGISTRY
    r.histogram("graft_elastic_rejoin_seconds",
                "Wall time of one checkpoint-streamed rejoin (fetch + "
                "validate + restore)", (),
                buckets=_CKPT_WRITE_BUCKETS).observe(seconds)
    if nbytes:
        r.gauge("graft_elastic_rejoin_last_bytes",
                "Snapshot bytes streamed by the last rejoin").set(nbytes)


def serve_shed(model, n=1):
    """Requests shed by the batcher because their deadline expired
    before dispatch (serving/batcher.py load shedding)."""
    if not enabled():
        return
    _REGISTRY.counter("graft_serve_shed_total",
                      "Serving requests shed at dispatch because their "
                      "deadline_ms had already expired", ("model",)).inc(
        n, model=model)


_REGISTRY.register_collector(_collect_device_memory)
_REGISTRY.register_collector(_collect_autograd_tape)
_REGISTRY.register_collector(_collect_engine_stats)


def compact_snapshot(reg=None):
    """Flat ``{"name{label=v}": value}`` view (histograms export their
    ``_count``/``_sum``) — the form the benches embed in BENCH JSON."""
    reg = reg or _REGISTRY
    out = {}
    reg._collect()
    for m in reg.metrics():
        for labels, payload in m.samples():
            key = m.name + _fmt_labels(labels)
            if m.kind == "histogram":
                out[m.name + "_count" + _fmt_labels(labels)] = \
                    payload["count"]
                out[m.name + "_sum" + _fmt_labels(labels)] = \
                    round(payload["sum"], 6)
            else:
                out[key] = payload
    return out


def write_snapshot(path, reg=None):
    """Dump the JSON snapshot to ``path`` (GRAFT_TELEMETRY_SNAPSHOT)."""
    reg = reg or _REGISTRY
    with open(path, "w") as f:
        json.dump(reg.snapshot(), f, indent=2, sort_keys=True)
    return path
