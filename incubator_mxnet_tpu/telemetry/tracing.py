"""Segment-aware tracing: chrome-trace glue between the deferred engine
and the profiler event stream.

Since the engine executes *bulk segments* (PR 1) rather than individual
ops, a per-op dispatch span times a ~0µs record and attributes nothing
to the flush that actually runs the program.  graftscope fixes the
attribution (the fusion-boundary view of "Operator Fusion in XLA",
PAPERS.md):

* every deferred op RECORD becomes a complete ("X") event with
  ``args={"deferred": true, "segment": <id>}`` — its duration is the
  record cost, never presented as op runtime;
* every segment FLUSH becomes a span (``bulk_segment_flush``, cat
  ``engine``) carrying cause / node count / program length / cache
  hit-miss, with ``device_time: true`` when ``profiler.sync`` blocked
  until ready (true device latency);
* chrome-trace flow events (``ph: "s"`` at record, ``ph: "f"`` at
  flush) link each deferred op to exactly one flush, so the trace UI
  draws arrows from where an op was *issued* to where its cost *landed*.

Also here: :func:`phase_span`, the per-batch training-loop span
(fwd/bwd/update/kvstore) used by gluon ``Trainer`` and
``Module.forward_backward`` — each span both lands in the chrome trace
(cat ``phase``) and feeds the ``graft_phase_seconds`` histogram.
"""
from __future__ import annotations

import itertools
import time

from . import blackbox as _blackbox
from . import lens as _lens
from . import metrics as _metrics

__all__ = ["phase_span", "next_segment_id", "record_active",
           "deferred_op_event", "segment_flush_span",
           "segment_summary", "validate_chrome_trace",
           "process_metadata_events", "trace_header"]

_segment_ids = itertools.count(1)

FLOW_NAME = "bulk"
FLOW_CAT = "engine.flow"
SEGMENT_SPAN = "bulk_segment_flush"


def next_segment_id():
    return next(_segment_ids)


def _prof():
    from .. import profiler
    return profiler


def record_active():
    """Whether deferred-op record events should be captured at all."""
    p = _prof()
    return p._P.active() and p.profile_imperative_enabled()


def _flow_id(segment, index):
    return "%d/%d" % (segment, index)


def deferred_op_event(name, begin_us, end_us, segment, index):
    """One deferred op record: the X event (marked deferred, owning
    segment) + the flow start binding it to the segment flush."""
    p = _prof()
    p.record_event(name, begin_us, end_us,
                   args={"deferred": True, "segment": segment})
    p.append_raw_event({"name": FLOW_NAME, "cat": FLOW_CAT, "ph": "s",
                        "id": _flow_id(segment, index), "ts": begin_us,
                        "pid": 0, "tid": 0})


def segment_flush_span(segment, cause, begin_us, end_us, flow_indices,
                       program_len, live_outputs, cache_hit, recorded,
                       device_time, error=False):
    """The flush span + one flow finish per op that emitted a flow start
    (``flow_indices`` — only those, so a profiler toggled mid-segment
    never leaves a dangling arrow).  ``error`` marks a flush whose
    replay raised — the span STILL closes its flow links so crash-time
    traces validate (no dangling ``s`` events)."""
    p = _prof()
    args = {"segment": segment, "cause": cause,
            "nodes": program_len,
            "live_outputs": live_outputs,
            "cache": "hit" if cache_hit else "miss",
            "recorded": bool(recorded),
            "device_time": bool(device_time)}
    step = _lens.current_step()
    if step is not None:
        args["step"] = step      # graftlens: flush → step attribution key
    if error:
        args["error"] = True
    p.record_event(SEGMENT_SPAN, begin_us, end_us, cat="engine", args=args)
    # bind each flow to the enclosing flush slice (bp: "e")
    ts = begin_us + min(1.0, max(end_us - begin_us, 0.0) / 2)
    for i in flow_indices:
        p.append_raw_event({"name": FLOW_NAME, "cat": FLOW_CAT, "ph": "f",
                            "bp": "e", "id": _flow_id(segment, i),
                            "ts": ts, "pid": 0, "tid": 0,
                            "args": {"segment": segment}})


class _PhaseSpan(object):
    """Times one training-loop phase; emits a chrome event (cat "phase")
    when the profiler runs and always feeds graft_phase_seconds.  The
    span closes on the exception path too — the chrome event (marked
    ``error``), the histogram observation AND the flight-recorder phase
    bracket all land, so a crash mid-phase leaves a well-formed trace
    and a dump that names the phase."""

    __slots__ = ("phase", "args", "_begin", "_t0", "_bb")

    def __init__(self, phase, args=None):
        self.phase = phase
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._begin = _prof()._now_us()
        self._bb = _blackbox.phase_begin(self.phase)
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        p = _prof()
        if p._P.active():
            args = {"phase": self.phase}
            if exc_type is not None:
                args["error"] = True
            if self.args:
                args.update(self.args)
            p.record_event(self.phase, self._begin, p._now_us(),
                           cat="phase", args=args)
        _metrics.phase(self.phase, dt)
        _lens.phase(self.phase, self._t0, self._t0 + dt)
        _blackbox.phase_end(self._bb, self.phase, dt,
                            error=exc_type is not None)
        return False


class _NullSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def phase_span(phase, args=None):
    """Context manager for one fwd/bwd/update/kvstore phase.  Free when
    the profiler, telemetry, the flight recorder AND the lens are all
    off."""
    if not _metrics.enabled() and not _prof()._P.active() \
            and not _blackbox.enabled() and not _lens.enabled():
        return _NULL
    return _PhaseSpan(phase, args)


# ---------------------------------------------------------------------------
# trace identity: process/thread metadata + wall-clock anchor
# ---------------------------------------------------------------------------

def process_metadata_events(rank=None, role=None, pid=None):
    """Chrome-trace ``M`` metadata events labeling this process's track
    (``process_name``/``process_sort_index``/``thread_name``).  The
    merged cross-rank trace (telemetry/aggregate.py) emits one set per
    rank so each rank renders as its own named process row; the profiler
    prepends a set to every single-rank dump so the merge can identify
    the rank without side channels."""
    if rank is None:
        rank = _blackbox._rank[0]
    name = "rank %d" % rank
    if role:
        name += " (%s)" % role
    if pid is None:
        pid = 0
    return [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": name}},
        {"name": "process_sort_index", "ph": "M", "pid": pid,
         "args": {"sort_index": int(rank)}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "main"}},
    ]


def trace_header():
    """(metadata events, otherData) for a chrome-trace dump.  The wall
    anchor maps the profiler's monotonic microsecond clock to wall-clock
    seconds, which is what lets the aggregator put N ranks' traces (and
    flight-recorder dumps) on one timeline."""
    from .. import profiler as _p
    other = {"rank": _blackbox._rank[0],
             "wall_anchor": {"perf_us": _p._now_us(),
                             "wall_s": time.time()}}
    if _blackbox._clock_offset[0] is not None:
        other["clock_offset_s"] = _blackbox._clock_offset[0]
    return process_metadata_events(), other


# ---------------------------------------------------------------------------
# trace analysis (CLI + smoke-tier validation)
# ---------------------------------------------------------------------------

def segment_summary(events, top=10):
    """Top-``top`` segment flushes by duration from a chrome-trace event
    list, plus per-cause totals — the fusion-boundary attribution view."""
    segs = [e for e in events
            if e.get("name") == SEGMENT_SPAN and e.get("ph") == "X"]
    segs.sort(key=lambda e: -e.get("dur", 0))
    causes = {}
    for e in segs:
        c = e.get("args", {}).get("cause", "?")
        agg = causes.setdefault(c, {"flushes": 0, "total_us": 0.0,
                                    "nodes": 0})
        agg["flushes"] += 1
        agg["total_us"] += e.get("dur", 0)
        agg["nodes"] += e.get("args", {}).get("nodes", 0)
    return {
        "top_segments": [{
            "segment": e.get("args", {}).get("segment"),
            "cause": e.get("args", {}).get("cause"),
            "nodes": e.get("args", {}).get("nodes"),
            "duration_us": round(e.get("dur", 0), 3),
            "cache": e.get("args", {}).get("cache"),
            "device_time": e.get("args", {}).get("device_time"),
        } for e in segs[:top]],
        "flush_causes_us": {c: round(v["total_us"], 3)
                            for c, v in causes.items()},
        "segments_total": len(segs),
    }


def validate_chrome_trace(trace):
    """Schema + flow-link validation of a dumped trace dict.  Returns a
    list of problems (empty == valid).  Used by the lint smoke tier.
    Accepts ``M`` metadata events (process_name/thread_name rows of
    merged cross-rank traces) and multi-hop flows (``s`` → any number of
    ``t`` steps → ``f``, the shape the cross-rank collective links
    use)."""
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    starts, finishes, hops = {}, {}, {}
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e or "name" not in e:
            problems.append("event %d: missing ph/name" % i)
            continue
        ph = e["ph"]
        if ph in ("X", "s", "t", "f", "i", "C") and "ts" not in e:
            problems.append("event %d (%s): missing ts" % (i, ph))
        if ph == "X" and e.get("dur", 0) < 0:
            problems.append("event %d: negative dur" % i)
        if ph == "M" and not isinstance(e.get("args"), dict):
            problems.append("event %d (M): missing args" % i)
        if ph == "s":
            starts.setdefault(e.get("id"), []).append(i)
        elif ph == "t":
            hops.setdefault(e.get("id"), []).append(i)
        elif ph == "f":
            finishes.setdefault(e.get("id"), []).append(i)
    for fid, idxs in starts.items():
        if len(idxs) != 1:
            problems.append("flow id %r started %d times" % (fid, len(idxs)))
        if fid not in finishes:
            problems.append("flow id %r never finishes" % fid)
    for fid, idxs in finishes.items():
        if len(idxs) != 1:
            problems.append("flow id %r finished %d times" % (fid, len(idxs)))
        if fid not in starts:
            problems.append("flow id %r finishes without a start" % fid)
    for fid in hops:
        if fid not in starts:
            problems.append("flow id %r has a step without a start" % fid)
    return problems
