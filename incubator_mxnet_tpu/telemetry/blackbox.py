"""graftwatch flight recorder — the always-on black box.

graftscope (metrics + tracing) only helps while the process is healthy
and a profiler is attached.  Production TPU jobs die differently: a
stalled collective, a device OOM, a worker that simply vanishes — and a
multi-hour run leaves nothing to debug with.  The flight recorder is the
answer: a bounded, lock-cheap ring buffer of structured events that is
ALWAYS recording (independent of ``GRAFT_TELEMETRY`` and the profiler)
and is dumped to JSON when the process dies or hangs:

* engine segment flushes (cause / node count / latency / cache),
* kvstore push/pull/reduce_many collectives (keys / bytes / rank),
* ``Trainer.step`` / ``Module.update`` boundaries with per-phase
  latencies and the device-memory highwater,
* dist heartbeats (per-worker last-seen + step skew) and watchdog trips.

Dump triggers: unhandled exception (``sys.excepthook`` chain), SIGTERM /
SIGINT (handler chain), an explicit :func:`dump` call, or a watchdog
trip (:mod:`~incubator_mxnet_tpu.telemetry.watchdog`).  The dump also
captures what was IN FLIGHT (the open engine flush / collective / phase
brackets) and the most recent bracket failures, so a crash mid-step
names the phase it died in.

Environment: ``GRAFT_BLACKBOX`` (default on) master switch;
``GRAFT_BLACKBOX_SIZE`` ring capacity (default 4096 events);
``GRAFT_BLACKBOX_PATH`` dump destination (default
``<tmpdir>/graft_blackbox.<pid>.json``).

Render a dump with ``python -m incubator_mxnet_tpu.telemetry
--blackbox PATH [--json]``; validate one with ``--blackbox --selftest``.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
import traceback
from collections import deque
from contextlib import nullcontext as _nullcontext

from . import lens as _lens
from ..analysis import lockstep as _lockstep

__all__ = ["enabled", "set_enabled", "record", "events", "stats",
           "in_flight", "inflight_entries", "progress", "last_progress",
           "collective", "phase_begin", "phase_end", "step_journal",
           "workers_seen", "set_rank", "set_clock_offset", "dump",
           "snapshot", "default_path", "validate_dump", "summarize_dump",
           "install_hooks", "configure", "selftest", "SCHEMA",
           "register_emergency", "unregister_emergency", "xray_session"]

SCHEMA = "graft-blackbox/1"
_DEFAULT_SIZE = 4096

_enabled_override = None


def set_enabled(flag):
    """Force the recorder on/off (None = defer to GRAFT_BLACKBOX)."""
    global _enabled_override
    _enabled_override = flag


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    return os.environ.get("GRAFT_BLACKBOX", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def _ring_size():
    try:
        n = int(os.environ.get("GRAFT_BLACKBOX_SIZE", str(_DEFAULT_SIZE)))
    except ValueError:
        return _DEFAULT_SIZE
    return max(n, 8)


# the ring: deque.append is GIL-atomic and O(1) with maxlen eviction —
# the hot path is one time.time() + one append, no lock
_ring = deque(maxlen=_ring_size())
_stats = [0]                    # events recorded ever (dropped = _stats[0]
#                                 - len(_ring)); single-slot list keeps the
#                                 increment one bytecode away from atomic —
#                                 a lost count under contention is harmless
_rank = [0]
_clock_offset = [None]          # latest heartbeat clock/arrival offset
#                                 estimate vs the freshest-arriving rank
#                                 (parallel/dist.py), recorded in dump
#                                 headers so aggregate.py can align
#                                 single dumps without matched anchors
_started_at = time.time()


def configure(size=None):
    """Re-size the ring (tests / live re-tuning).  Keeps newest events."""
    global _ring
    if size is not None:
        os.environ["GRAFT_BLACKBOX_SIZE"] = str(int(size))
    _ring = deque(_ring, maxlen=_ring_size())


def set_rank(rank):
    """Stamp the dist rank onto every future dump (parallel/dist.py)."""
    _rank[0] = int(rank)


def set_clock_offset(seconds):
    """Record this rank's latest clock/arrival offset estimate from the
    dist heartbeat (upper bound: includes arrival skew).  Lands in dump
    headers as ``clock_offset_s`` for the cross-rank aggregator."""
    _clock_offset[0] = float(seconds)


def record(kind, **fields):
    """Append one structured event.  THE hot path: a disabled recorder
    costs one env lookup; an enabled one adds one tuple + deque append.
    graftlens threads its step id through: every event recorded from a
    thread with lens activity carries ``step`` — the join key the
    cross-rank aggregator uses."""
    if not enabled():
        return
    if "step" not in fields:
        step = _lens.current_step()
        if step is not None:
            fields["step"] = step
    _stats[0] += 1
    _ring.append((time.time(), kind, fields))


def events():
    """Snapshot of the ring as dicts (oldest first)."""
    return [{"ts": t, "kind": k, "data": dict(f)} for t, k, f in list(_ring)]


def stats():
    """Recorder status summary (benches embed this)."""
    counts = {}
    for _t, k, _f in list(_ring):
        counts[k] = counts.get(k, 0) + 1
    return {"enabled": enabled(), "ring_size": _ring_size(),
            "events_held": len(_ring), "events_total": _stats[0],
            "counts": counts}


# ---------------------------------------------------------------------------
# in-flight brackets: what the process was DOING when it died/hung
# ---------------------------------------------------------------------------

_inflight_lock = threading.Lock()
_inflight = {}                  # thread ident -> [entry dict, ...] (stack)
_failures = deque(maxlen=16)    # brackets that exited with an exception
_last_progress = [time.time(), "startup"]


def progress(site):
    """A bracket completed: wall-clock progress for the watchdog."""
    _last_progress[0] = time.time()
    _last_progress[1] = site


def last_progress():
    return {"ts": _last_progress[0], "site": _last_progress[1],
            "age": time.time() - _last_progress[0]}


def _push_inflight(site, detail):
    tid = threading.get_ident()
    # the numeric ident rides the entry so the watchdog's typed
    # escalation (GRAFT_WATCHDOG_ESCALATE) can raise into the exact
    # thread that owns the stuck bracket
    entry = {"site": site, "detail": detail, "since": time.time(),
             "thread": threading.current_thread().name, "tid": tid}
    with _inflight_lock:
        _inflight.setdefault(tid, []).append(entry)
    return entry


def _pop_inflight(entry, error=None):
    tid = threading.get_ident()
    with _inflight_lock:
        stack = _inflight.get(tid)
        if stack:
            try:
                stack.remove(entry)
            except ValueError:
                pass
            if not stack:
                _inflight.pop(tid, None)
    if error is not None:
        _failures.append(dict(entry, error=error,
                              seconds=time.time() - entry["since"]))
    else:
        progress(entry["site"])


def inflight_entries():
    """Live references to the open bracket entries (the watchdog marks
    tripped ones in place)."""
    with _inflight_lock:
        return [e for stack in _inflight.values() for e in stack]


_NULL = _nullcontext()          # stateless: safe to share across threads


class _InFlight(object):
    __slots__ = ("site", "detail", "entry")

    def __init__(self, site, detail):
        self.site = site
        self.detail = detail
        self.entry = None

    def __enter__(self):
        self.entry = _push_inflight(self.site, self.detail)
        return self

    def __exit__(self, et, ev, tb):
        _pop_inflight(self.entry, error=repr(ev) if et is not None else None)
        return False


def in_flight(site, detail=None):
    """Bracket one potentially-hanging operation (engine flush, dist
    collective): the watchdog times these, and an open bracket at dump
    time IS the "what was it doing" answer."""
    if not enabled():
        return _NULL
    return _InFlight(site, detail or {})


# ---------------------------------------------------------------------------
# collectives: kvstore push/pull/reduce_many brackets + slow-call EWMA
# ---------------------------------------------------------------------------

_ewma_lock = threading.Lock()
_ewma = {}                      # path -> EWMA seconds
_EWMA_FLOOR = 1e-3              # ignore sub-ms noise for straggler calls
# async brackets stay open from issue until the consumer waits, so their
# "latency" measures how long the result was LEFT in flight (graftlap:
# mostly the rest of the backward pass; graftduplex pulls: until the
# next forward first touches a weight), not wire health — feeding that
# into the straggler EWMA would cry wolf on every well-overlapped step
_NO_STRAGGLER_PATHS = frozenset(["reduce_many_async", "pull_many_async"])


def _straggler_factor():
    try:
        return float(os.environ.get("GRAFT_STRAGGLER_FACTOR", "3"))
    except ValueError:
        return 3.0


# collective sequence numbers: one process-wide monotonic counter.  The
# collective issue order is SPMD-identical across ranks (the lockstep
# contract every dist path already keeps), so the same seq on two ranks
# IS the same wire collective — the matching key the cross-rank trace
# aggregator and straggler table join on.
_collective_seq = itertools.count(1)


class _Collective(object):
    __slots__ = ("path", "fields", "entry", "_t0", "_bb")

    def __init__(self, path, fields, bb=True):
        self.path = path
        self.fields = fields
        self.entry = None
        self._bb = bb           # False: recorder off, bracket kept alive
        #                         only for graftlens + chrome spans

    def __enter__(self):
        self._t0 = time.perf_counter()
        seq = next(_collective_seq)
        fields = dict(self.fields, seq=seq)
        step = _lens.current_step()
        if step is not None:
            fields["step"] = step
        self.fields = fields
        # lockstep divergence auditor: fold this collective's identity
        # into the rank's rolling stream hash at the moment its seq is
        # assigned (the SPMD issue order IS what the hash witnesses);
        # host-service ps_* paths are excluded inside fold()
        _lockstep.fold(seq, self.path, n_keys=fields.get("n_keys"),
                       nbytes=fields.get("nbytes"),
                       keys=fields.get("keys")
                       or ([fields["bucket"]] if fields.get("bucket")
                           else None))
        if self._bb:
            self.entry = _push_inflight(
                "collective", dict(fields, path=self.path))
        return self

    def __exit__(self, et, ev, tb):
        dt = time.perf_counter() - self._t0
        err = repr(ev) if et is not None else None
        if self._bb:
            _pop_inflight(self.entry, error=err)
            fields = dict(self.fields, path=self.path, rank=_rank[0],
                          latency_ms=round(dt * 1e3, 3))
            if err is not None:
                fields["error"] = err
            record("collective", **fields)
        # graftlens: a sync bracket blocks the host for its whole span —
        # blocked == in-flight.  Async issues (reduce_many_async) are
        # excluded: their bracket stays open across healthy overlap and
        # the REAL blocked/in-flight split is reported by
        # ReduceHandle.wait on the consumer side.
        if self.path not in _NO_STRAGGLER_PATHS:
            _lens.comm(self._t0, self._t0 + dt)
        self._trace_span(dt)
        if self._bb and err is None:
            self._straggler_check(dt)
        return False

    def _trace_span(self, dt):
        """Chrome-trace collective span (cat ``collective``) so traces —
        not just flight-recorder dumps — carry the per-collective
        enter/exit the cross-rank aggregator keys on."""
        from .. import profiler as _prof
        if not _prof._P.active():
            return
        end_us = _prof._now_us()
        args = {"path": self.path, "rank": _rank[0]}
        for k in ("seq", "step", "n_keys", "nbytes", "bucket"):
            if self.fields.get(k) is not None:
                args[k] = self.fields[k]
        _prof.record_event(self.fields.get("bucket") or self.path,
                           end_us - dt * 1e6, end_us, cat="collective",
                           args=args)

    def _straggler_check(self, dt):
        """Slow-collective detection: a call beyond ``factor`` × its own
        EWMA (per path) earns a log line + a ring event.  The EWMA only
        updates on healthy calls so one straggler can't poison it."""
        if self.path in _NO_STRAGGLER_PATHS:
            return
        factor = _straggler_factor()
        with _ewma_lock:
            prev = _ewma.get(self.path)
            slow = (prev is not None and prev > _EWMA_FLOOR
                    and dt > factor * prev)
            if not slow:
                _ewma[self.path] = dt if prev is None \
                    else 0.8 * prev + 0.2 * dt
        if slow:
            record("slow_collective", path=self.path, rank=_rank[0],
                   latency_ms=round(dt * 1e3, 3),
                   ewma_ms=round(prev * 1e3, 3), factor=factor)
            from . import metrics as _metrics
            _metrics.collective_slow(self.path)
            import logging
            logging.getLogger("graftwatch").warning(
                "slow collective: %s took %.1fms (EWMA %.1fms, factor %g) "
                "on rank %d", self.path, dt * 1e3, prev * 1e3, factor,
                _rank[0])


def collective(path, **fields):
    """Bracket one kvstore collective (push/pull/reduce_many/ps_*):
    records a ``collective`` ring event with latency + key/byte counts,
    feeds the straggler EWMA, and shows up in-flight while running.
    With the recorder off, graftlens' comm accounting and the profiler's
    chrome collective spans must survive — the bracket then runs in
    light mode (no ring/in-flight/EWMA, same seq/step stamping)."""
    if enabled():
        return _Collective(path, fields)
    if _lens.enabled() or _profiler_active():
        return _Collective(path, fields, bb=False)
    return _NULL


def _profiler_active():
    from .. import profiler as _prof
    return _prof._P.active()


# ---------------------------------------------------------------------------
# step journal: Trainer.step / Module.update boundaries with phase latencies
# ---------------------------------------------------------------------------

_tls = threading.local()
_step_counters = {}


def phase_begin(phase):
    """Called by tracing._PhaseSpan.__enter__: the phase becomes an
    in-flight bracket so a crash/hang mid-phase names it."""
    if not enabled():
        return None
    return _push_inflight("phase", {"phase": phase})


def phase_end(entry, phase, seconds, error=False):
    """Close the phase bracket; latency lands on the open step journal
    (or its own ring event when no step is open, e.g. Module fwd/bwd)."""
    if entry is not None:
        _pop_inflight(entry, error="exception in phase %r" % phase
                      if error else None)
    if not enabled():
        return
    j = getattr(_tls, "step", None)
    if j is not None:
        j["phases"][phase] = j["phases"].get(phase, 0.0) + seconds
        if error:
            j["error_phase"] = phase
    else:
        fields = {"phase": phase, "seconds": round(seconds, 6)}
        if error:
            fields["error"] = True
        record("phase", **fields)


def _device_mem_peak():
    """Cheap device-memory highwater: allocator counters only (the
    live_arrays fallback walk is too slow for a per-step journal)."""
    try:
        import jax
        total, found = 0, False
        for d in jax.local_devices():
            s = d.memory_stats() or {}
            if "peak_bytes_in_use" in s:
                total += int(s.get("peak_bytes_in_use", 0))
                found = True
        return total if found else None
    except Exception:
        return None


class _StepJournal(object):
    __slots__ = ("origin", "fields", "entry", "journal", "prev", "_t0")

    def __init__(self, origin, fields):
        self.origin = origin
        self.fields = fields

    def __enter__(self):
        index = _step_counters[self.origin] = \
            _step_counters.get(self.origin, 0) + 1
        self._t0 = time.perf_counter()
        self.journal = {"phases": {}}
        self.prev = getattr(_tls, "step", None)
        _tls.step = self.journal
        self.entry = _push_inflight(
            "step", dict(self.fields, origin=self.origin, index=index))
        return self

    def __exit__(self, et, ev, tb):
        _tls.step = self.prev
        err = repr(ev) if et is not None else None
        _pop_inflight(self.entry, error=err)
        fields = dict(self.fields, origin=self.origin,
                      index=self.entry["detail"]["index"],
                      latency_ms=round(
                          (time.perf_counter() - self._t0) * 1e3, 3),
                      phases={k: round(v, 6)
                              for k, v in self.journal["phases"].items()})
        mem = _device_mem_peak()
        if mem is not None:
            fields["device_mem_peak"] = mem
        if "error_phase" in self.journal:
            fields["error_phase"] = self.journal["error_phase"]
        if err is not None:
            fields["error"] = err
        # graftlens: the journal boundary IS the step-window boundary —
        # finalize the attribution window and fold the component
        # breakdown into this ring event (the step event's `step` field
        # then matches the id stamped on the window's flushes/collectives)
        lens_rec = _lens.step_end(self.origin, extra=_lens_extra(self.fields))
        if lens_rec is not None:
            fields["step"] = lens_rec["step"]
            fields["lens"] = _lens.compact(lens_rec)
        record("step", **fields)
        return False


def _lens_extra(fields):
    extra = {k: fields[k]
             for k in ("overlapped", "fused", "batch_size", "compiled")
             if k in fields}
    return extra or None


class _LensOnlyStep(object):
    """Step boundary for graftlens when the flight recorder is off: the
    lens window must still close at step end (components would otherwise
    pile into one endless first step)."""

    __slots__ = ("origin", "fields")

    def __init__(self, origin, fields):
        self.origin = origin
        self.fields = fields

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        _lens.step_end(self.origin, extra=_lens_extra(self.fields))
        return False


def step_journal(origin, **fields):
    """Bracket one optimizer step (gluon ``Trainer.step`` /
    ``Module.update``): phase latencies recorded inside land on ONE
    ``step`` ring event with the device-memory highwater — and the
    journal exit closes the graftlens attribution window (which keeps
    working when the recorder itself is disabled)."""
    if not enabled():
        if _lens.enabled():
            return _LensOnlyStep(origin, fields)
        return _NULL
    return _StepJournal(origin, fields)


def xray_session(reason, steps, phases, **extra):
    """One graftxray capture session (kind ``xray_capture``): the
    phase→device-seconds table a compiled-step profiler capture
    attributed, plus its conservation verdict and top ops — the
    flight-recorder twin of the ``graft_xray_phase_device_seconds``
    gauges, so a post-mortem dump carries the last in-program device
    decomposition alongside the host-side step journals."""
    if not enabled():
        return
    record("xray_capture", reason=reason, steps=steps, phases=phases,
           **{k: v for k, v in extra.items() if v is not None})


# ---------------------------------------------------------------------------
# dist worker table (straggler view)
# ---------------------------------------------------------------------------

_workers_lock = threading.Lock()
_workers = {}                   # rank -> {"step", "lag_s", "at"}


def workers_seen(table, skew=None, step=None):
    """Update the per-worker last-seen table from one dist heartbeat
    (parallel/dist.py piggybacks it on the kvstore sync path)."""
    if not enabled():
        return
    now = time.time()
    with _workers_lock:
        for r, info in table.items():
            _workers[int(r)] = dict(info, at=now)
    fields = {"workers": len(table)}
    if skew is not None:
        fields["skew_s"] = round(float(skew), 6)
    if step is not None:
        fields["step"] = int(step)
    record("dist_heartbeat", **fields)


# ---------------------------------------------------------------------------
# dump
# ---------------------------------------------------------------------------

def default_path():
    """Dump destination.  A shared ``GRAFT_BLACKBOX_PATH`` is suffixed
    with the dist rank for ranks > 0 — N workers honoring the same env
    var used to overwrite each other's post-mortems; now rank 0 keeps
    the configured path (single-process behavior unchanged) and every
    other rank writes ``<stem>.rank<r><ext>`` alongside it, ready for
    ``--analyze`` to consume the whole set.  A ``{rank}`` placeholder
    substitutes exactly; a path whose filename already names this rank
    (``rank<r>`` in the basename — the old per-worker guidance) is kept
    verbatim, so existing per-rank deployments keep their paths."""
    path = os.environ.get("GRAFT_BLACKBOX_PATH")
    if path:
        if "{rank}" in path:
            return path.replace("{rank}", str(_rank[0]))
        if _rank[0] and "rank%d" % _rank[0] not in os.path.basename(path):
            root, ext = os.path.splitext(path)
            path = "%s.rank%d%s" % (root, _rank[0], ext)
        return path
    return os.path.join(
        tempfile.gettempdir(), "graft_blackbox.%d.json" % os.getpid())


def _thread_stacks():
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in frames.items():
        label = "%s (%d)" % (names.get(ident, "?"), ident)
        out[label] = traceback.format_stack(frame)
    return out


def snapshot(reason="manual", extra=None):
    """The dump document (JSON-able).  Includes the ring, the open
    in-flight brackets, recent bracket failures, the per-worker
    last-seen table, and formatted thread stacks."""
    now = time.time()
    with _inflight_lock:
        infl = [dict(e, age=round(now - e["since"], 6))
                for stack in _inflight.values() for e in stack]
    with _workers_lock:
        workers = {str(r): dict(v) for r, v in _workers.items()}
    doc = {
        "schema": SCHEMA,
        "pid": os.getpid(),
        "rank": _rank[0],
        "clock_offset_s": _clock_offset[0],
        "reason": reason,
        "dumped_at": now,
        "started_at": _started_at,
        "ring_size": _ring_size(),
        "events_total": _stats[0],
        "last_progress": last_progress(),
        "in_flight": infl,
        "failures": [dict(f) for f in _failures],
        "workers": workers,
        "events": events(),
        "threads": _thread_stacks(),
    }
    try:
        # the lockstep divergence table rides every dump: a watchdog
        # hang dump then carries the per-seq collective stream for
        # telemetry --analyze to pinpoint the divergent rank offline
        doc["lockstep"] = _lockstep.snapshot()
    except Exception:
        pass                    # a dying process must still dump
    if extra:
        doc.update(extra)
    return doc


def dump(path=None, reason="manual", extra=None):
    """Write the flight-recorder dump; returns the path (or None when
    the write failed — a dying process must not die twice)."""
    path = path or default_path()
    doc = snapshot(reason=reason, extra=extra)
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True, default=str)
    except OSError:
        return None
    record("dump", path=path, reason=reason)
    return path


# ---------------------------------------------------------------------------
# crash hooks: unhandled exception + SIGTERM/SIGINT
# ---------------------------------------------------------------------------

_hooks_installed = [False]
_signals_installed = [False]
_prev_excepthook = None
_prev_signals = {}
_emergency_callbacks = []       # run best-effort on SIGTERM/SIGINT BEFORE
#                                 the dump (graftarmor emergency snapshot)


def register_emergency(fn):
    """Register a callback the signal handler runs (best-effort, before
    the flight-recorder dump) when the process is being terminated —
    the armor checkpointer hangs its emergency snapshot here.  Errors
    are swallowed: a dying process must still dump and exit."""
    if fn not in _emergency_callbacks:
        _emergency_callbacks.append(fn)
    return fn


def unregister_emergency(fn):
    try:
        _emergency_callbacks.remove(fn)
    except ValueError:
        pass


def _excepthook(exc_type, exc, tb):
    try:
        if enabled() and (_ring or inflight_entries()):
            frames = traceback.format_exception(exc_type, exc, tb)
            dump(reason="exception", extra={"exception": {
                "type": getattr(exc_type, "__name__", str(exc_type)),
                "value": str(exc),
                "traceback": frames[-20:],
            }})
    except Exception:
        pass                    # never mask the original crash
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _signal_handler(signum, frame):
    for fn in list(_emergency_callbacks):
        try:
            fn(signum)
        except Exception:
            pass                # emergency work is best-effort only
    try:
        if enabled() and (_ring or inflight_entries()):
            dump(reason="signal:%d" % signum)
    except Exception:
        pass
    prev = _prev_signals.get(signum)
    import signal as _signal
    if callable(prev):
        prev(signum, frame)
    else:
        # restore the default disposition and re-raise so the exit code
        # still says "killed by signal"
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_hooks():
    """Chain the excepthook + SIGTERM/SIGINT handlers (idempotent).  A
    signal the process explicitly IGNORES (SIG_IGN — e.g. worker pools
    parking SIGINT) is left alone: chaining over it would turn an
    ignored signal fatal.  A non-main-thread call skips the signal half
    WITHOUT latching it, so a later main-thread call (telemetry re-init,
    ``watchdog.start``) still gets to install the handlers."""
    global _prev_excepthook
    if not _hooks_installed[0]:
        _hooks_installed[0] = True
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
    if _signals_installed[0]:
        return
    import signal as _signal
    try:
        for signum in (_signal.SIGTERM, _signal.SIGINT):
            if signum not in _prev_signals \
                    and _signal.getsignal(signum) is not _signal.SIG_IGN:
                _prev_signals[signum] = _signal.signal(signum,
                                                       _signal_handler)
        _signals_installed[0] = True
    except ValueError:          # not the main thread: retry later
        pass


# ---------------------------------------------------------------------------
# dump validation + summary (the --blackbox CLI rides these)
# ---------------------------------------------------------------------------

def validate_dump(doc):
    """Schema check of a dump document.  Returns a list of problems
    (empty == valid) — same contract as tracing.validate_chrome_trace."""
    problems = []
    if not isinstance(doc, dict):
        return ["dump is not a JSON object"]
    if doc.get("schema") != SCHEMA:
        problems.append("schema is %r, expected %r"
                        % (doc.get("schema"), SCHEMA))
    for key, typ in (("pid", int), ("reason", str), ("dumped_at", (int, float)),
                     ("ring_size", int), ("events_total", int),
                     ("events", list), ("in_flight", list),
                     ("failures", list), ("workers", dict),
                     ("last_progress", dict)):
        if key not in doc:
            problems.append("missing key %r" % key)
        elif not isinstance(doc[key], typ):
            problems.append("key %r has type %s" % (key,
                                                    type(doc[key]).__name__))
    for i, e in enumerate(doc.get("events") or []):
        if not isinstance(e, dict):
            problems.append("event %d: not an object" % i)
            continue
        if not isinstance(e.get("ts"), (int, float)):
            problems.append("event %d: missing/invalid ts" % i)
        if not isinstance(e.get("kind"), str) or not e.get("kind"):
            problems.append("event %d: missing/invalid kind" % i)
        if not isinstance(e.get("data"), dict):
            problems.append("event %d: missing/invalid data" % i)
    for i, e in enumerate(doc.get("in_flight") or []):
        if not isinstance(e, dict) or "site" not in e or "since" not in e:
            problems.append("in_flight %d: missing site/since" % i)
    if isinstance(doc.get("events"), list) and \
            isinstance(doc.get("events_total"), int) and \
            doc["events_total"] < len(doc["events"]):
        problems.append("events_total < events held (counter went backwards)")
    return problems


def summarize_dump(doc, last=10):
    """Reconstruct the final timeline from a dump: the last flushes,
    steps and collectives, what was in flight, per-worker last-seen."""
    evs = doc.get("events") or []
    t_dump = doc.get("dumped_at", 0.0)

    def tail(kind, n=last):
        rows = [e for e in evs if e.get("kind") == kind]
        return [dict(e["data"], age_s=round(t_dump - e["ts"], 3))
                for e in rows[-n:]]

    counts = {}
    for e in evs:
        counts[e.get("kind", "?")] = counts.get(e.get("kind", "?"), 0) + 1
    workers = {r: dict(v, info_age_s=round(t_dump - v.get("at", t_dump), 3))
               for r, v in (doc.get("workers") or {}).items()}
    # graftstep/graftguard: the compiled-path view — how many journaled
    # steps ran compiled, the last trace/miss/ineligible transitions
    # (each miss names the churned guard component), and any EH3xx
    # auditor reports
    step_rows = [e for e in evs if e.get("kind") == "step"]
    compiled = {
        "steps_compiled": sum(1 for e in step_rows
                              if e["data"].get("compiled")),
        "steps_total": len(step_rows),
        "last_transitions": tail("step_compile", 5),
        "auditor_reports": tail("compile_check", 5),
    }
    return {
        "reason": doc.get("reason"),
        "pid": doc.get("pid"),
        "rank": doc.get("rank"),
        "dumped_at": t_dump,
        "events_total": doc.get("events_total"),
        "events_held": len(evs),
        "counts": counts,
        "last_progress": doc.get("last_progress"),
        "in_flight": doc.get("in_flight") or [],
        "failures": doc.get("failures") or [],
        "last_flushes": tail("engine_flush"),
        "last_steps": tail("step", 5),
        "compiled": compiled,
        "last_collectives": tail("collective", 5),
        "slow_collectives": tail("slow_collective", 5),
        "watchdog": doc.get("watchdog"),
        "exception": doc.get("exception"),
        "workers": workers,
    }


def selftest():
    """Exercise the full recorder pipeline on a tiny real workload and
    validate the dump schema (the lint smoke tier).  Returns a list of
    problems — empty means pass."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine

    prev_override = _enabled_override
    prev_size = os.environ.get("GRAFT_BLACKBOX_SIZE")
    set_enabled(True)
    configure(size=_DEFAULT_SIZE)   # pin: an ambient tiny ring (legal
    #                                 config) must not evict the events
    #                                 this smoke asserts on
    held = None
    path = None
    try:
        a = mx.nd.array(np.ones((4, 4), np.float32))
        for _ in range(10):                      # >= 8 engine_flush events
            with engine.bulk(8):
                ((a * a) + a).asnumpy()
        kv = mx.kv.create("local")
        kv.init("bb", mx.nd.ones((4,)))
        kv.push("bb", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("bb", out=out)
        with step_journal("selftest", batch_size=1):
            from . import tracing
            with tracing.phase_span("update"):
                (a + 1).asnumpy()
        held = _push_inflight("selftest", {"why": "held open across dump"})
        fd, path = tempfile.mkstemp(suffix=".json", prefix="graft_bb_self_")
        os.close(fd)
        dump(path=path, reason="selftest")
        with open(path) as f:
            doc = json.load(f)
        problems = validate_dump(doc)
        flushes = [e for e in doc["events"] if e["kind"] == "engine_flush"]
        if len(flushes) < 8:
            problems.append("expected >= 8 engine_flush events, got %d"
                            % len(flushes))
        if not any(e["kind"] == "collective" for e in doc["events"]):
            problems.append("no collective events (kvstore brackets gone)")
        steps = [e for e in doc["events"] if e["kind"] == "step"]
        if not steps:
            problems.append("no step events (step journal gone)")
        elif "update" not in steps[-1]["data"].get("phases", {}):
            problems.append("step event lost its phase latencies")
        if not any(e.get("site") == "selftest" for e in doc["in_flight"]):
            problems.append("held-open bracket missing from in_flight")
        try:
            summarize_dump(doc)
        except Exception as exc:
            problems.append("summarize_dump raised: %r" % exc)
        return problems
    finally:
        if held is not None:
            _pop_inflight(held)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass
        set_enabled(prev_override)
        if prev_size is None:
            os.environ.pop("GRAFT_BLACKBOX_SIZE", None)
        else:
            os.environ["GRAFT_BLACKBOX_SIZE"] = prev_size
        configure()
