"""graftscope + graftwatch — observability for the deferred engine.

Four quarters (see docs/observability.md for the full guide):

* :mod:`~incubator_mxnet_tpu.telemetry.tracing` — chrome-trace spans per
  bulk-segment flush with flow links from each deferred op's record
  event, so a trace of a bulked model body shows *where* cost actually
  lands (the profiler still owns the event buffer and ``dump()``).
* :mod:`~incubator_mxnet_tpu.telemetry.metrics` — the process-wide
  Counter/Gauge/Histogram registry (engine flush causes, kvstore bytes
  and compression ratio, io batches/sec, autograd tape sizes, device
  memory, training phase latencies, watchdog/dist liveness) with JSON
  snapshot and Prometheus text expositions.
* :mod:`~incubator_mxnet_tpu.telemetry.blackbox` — the always-on flight
  recorder: a bounded ring of structured events (engine flushes,
  kvstore collectives, step boundaries, dist heartbeats) dumped to JSON
  on unhandled exception, SIGTERM/SIGINT, ``blackbox.dump()`` or a
  watchdog trip.  Independent of ``GRAFT_TELEMETRY`` and the profiler.
* :mod:`~incubator_mxnet_tpu.telemetry.watchdog` — the hang watchdog: a
  thread that trips when an engine flush / dist collective / phase stays
  in flight past ``GRAFT_WATCHDOG_TIMEOUT``, writing the dump + thread
  stacks (and aborting under ``GRAFT_WATCHDOG_ABORT``).
* :mod:`~incubator_mxnet_tpu.telemetry.lens` — graftlens per-step
  wall-time attribution (data_wait/forward/backward_compute/
  exposed_comm/optimizer_update/host_gap, conserving the step wall
  clock), kept in a ring of the last ``GRAFT_LENS_RING`` steps and
  printable every ``GRAFT_STEP_REPORT`` steps.
* :mod:`~incubator_mxnet_tpu.telemetry.aggregate` — cross-rank trace
  merging: N per-rank chrome traces / blackbox dumps → ONE merged trace
  with per-rank tracks, cross-rank flow links per collective, and a
  straggler table (last-to-enter/exit rank + spreads).

CLI::

    python -m incubator_mxnet_tpu.telemetry --summary [--json]
    python -m incubator_mxnet_tpu.telemetry --blackbox PATH [--json]
    python -m incubator_mxnet_tpu.telemetry --steps [--json]
    python -m incubator_mxnet_tpu.telemetry --analyze R0.json R1.json \
        [--json | --merged OUT.json]

Environment: ``GRAFT_TELEMETRY=0`` disables metric collection;
``GRAFT_TELEMETRY_SNAPSHOT=<path>`` writes the JSON snapshot at process
exit; ``GRAFT_TELEMETRY_TOPK`` sets the CLI's segment table size;
``GRAFT_BLACKBOX[_SIZE|_PATH]`` control the flight recorder;
``GRAFT_WATCHDOG_TIMEOUT``/``GRAFT_WATCHDOG_ABORT`` the watchdog.
"""
from __future__ import annotations

import os as _os

from . import metrics
from . import lens
from . import tracing
from . import blackbox
from . import watchdog
from . import aggregate
from . import xray
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      compact_snapshot, enabled, parse_prometheus_text,
                      registry, set_enabled, write_snapshot)
from .tracing import phase_span

__all__ = ["metrics", "lens", "tracing", "blackbox", "watchdog",
           "aggregate", "xray",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "enabled", "set_enabled", "parse_prometheus_text",
           "compact_snapshot", "write_snapshot", "phase_span"]

_snapshot_path = _os.environ.get("GRAFT_TELEMETRY_SNAPSHOT")
if _snapshot_path:
    import atexit as _atexit

    _atexit.register(lambda: write_snapshot(_snapshot_path))

# graftwatch is ALWAYS-ON by default: the crash hooks (excepthook +
# SIGTERM/SIGINT chains) install unconditionally — they re-check
# enabled() at fire time and only write a dump when the recorder holds
# events, so a process that starts with GRAFT_BLACKBOX=0 and calls
# blackbox.set_enabled(True) later still gets its post-mortem.  The
# watchdog thread only starts when GRAFT_WATCHDOG_TIMEOUT asks for it.
blackbox.install_hooks()
watchdog.maybe_start()
