"""graftscope — segment-aware tracing + unified metrics for the deferred
engine.

Two halves (see docs/observability.md for the full guide):

* :mod:`~incubator_mxnet_tpu.telemetry.tracing` — chrome-trace spans per
  bulk-segment flush with flow links from each deferred op's record
  event, so a trace of a bulked model body shows *where* cost actually
  lands (the profiler still owns the event buffer and ``dump()``).
* :mod:`~incubator_mxnet_tpu.telemetry.metrics` — the process-wide
  Counter/Gauge/Histogram registry (engine flush causes, kvstore bytes
  and compression ratio, io batches/sec, autograd tape sizes, device
  memory, training phase latencies) with JSON snapshot and Prometheus
  text expositions.

CLI::

    python -m incubator_mxnet_tpu.telemetry --summary [--json]

Environment: ``GRAFT_TELEMETRY=0`` disables metric collection;
``GRAFT_TELEMETRY_SNAPSHOT=<path>`` writes the JSON snapshot at process
exit; ``GRAFT_TELEMETRY_TOPK`` sets the CLI's segment table size.
"""
from __future__ import annotations

import os as _os

from . import metrics
from . import tracing
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      compact_snapshot, enabled, parse_prometheus_text,
                      registry, set_enabled, write_snapshot)
from .tracing import phase_span

__all__ = ["metrics", "tracing", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "registry", "enabled", "set_enabled",
           "parse_prometheus_text", "compact_snapshot", "write_snapshot",
           "phase_span"]

_snapshot_path = _os.environ.get("GRAFT_TELEMETRY_SNAPSHOT")
if _snapshot_path:
    import atexit as _atexit

    _atexit.register(lambda: write_snapshot(_snapshot_path))
