"""graftlens — per-step wall-time attribution.

graftscope tells you what each *span* cost and graftwatch tells you what
the process was doing when it died — but neither answers the question
that drives every perf decision on this roadmap: **where did this step's
wall time go?**  (EQuARX shows collective cost dominating distributed
step time; the XLA fusion analysis shows device-time attribution is the
prerequisite to every fusion/overlap decision — both need a per-step
decomposition, not a pile of spans.)

The lens decomposes every training step's wall clock into six components
that sum EXACTLY to the step's wall time (the conservation contract,
enforced by tests/test_lens.py):

* ``data_wait``         — blocked in ``DataIter.next()`` / ``DataLoader``
                          waiting for a batch,
* ``forward``           — inside ``autograd.record()`` scopes and/or the
                          ``fwd`` phase span (Module),
* ``backward_compute``  — the ``bwd`` phase span (``autograd.backward``),
* ``exposed_comm``      — host time *visibly* spent on communication:
                          sync kvstore collective brackets,
                          ``ReduceHandle.wait`` blocks, and the trainer's
                          ``kvstore`` phase (reduce packing + waits),
* ``optimizer_update``  — the ``update`` phase span,
* ``host_gap``          — everything else (python glue, metric updates,
                          logging, user code between batches).

A *step window* runs from the end of the previous ``Trainer.step`` /
``Module.update`` journal to the end of the current one, so the data
fetch and forward of batch N land on step N — the whole loop is
attributed, not just the optimizer call.  Sources report timestamped
intervals; at step end the window is swept once and every elementary
slice is attributed to the highest-priority covering category
(``exposed_comm > optimizer_update > backward_compute > forward >
data_wait``), so overlapping instrumentation (a collective bracket
inside the kvstore phase, a record scope around a fwd span) can never
double-count.  ``host_gap`` is the residual — the six components sum to
the window by construction.

Separately from the swept component, every step carries
``comm_blocked_s`` (host time blocked in collectives) and
``comm_inflight_s`` (summed issue→wait-return wall time of the same
collectives — an upper bound on issue→ready, the same convention as
graftlap's ``graft_trainer_overlap_ratio``).  On the serial reduce path
the two are EQUAL by construction; under graftlap overlap
``comm_blocked_s < comm_inflight_s`` — the difference bounds the
communication hidden under backward.

Steps live in an in-process ring of the last ``GRAFT_LENS_RING``
(default 64) records, are published as ``graft_lens_*``
gauges/histograms, are folded into the graftwatch step journal (the
``lens`` field of ``step`` ring events), and — with
``GRAFT_STEP_REPORT=N`` — print a one-line attribution report to stderr
every N steps.  ``python -m incubator_mxnet_tpu.telemetry --steps``
renders the ring; ``--analyze`` (telemetry/aggregate.py) merges
per-rank artifacts into one cross-rank trace with straggler analytics.

Master switch: ``GRAFT_LENS`` (default on; ``set_enabled`` overrides).
The hot path per source event is one ``perf_counter`` + one list append;
``lens_overhead_pct`` in ``bench_eager.py`` keeps the cost under the 2%
bar.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = ["enabled", "set_enabled", "ring_size", "configure", "interval",
           "phase", "io_wait", "comm", "device", "step_end", "current_step",
           "steps", "summary", "compact", "reset", "COMPONENTS", "ABBREV"]

COMPONENTS = ("data_wait", "forward", "backward_compute", "exposed_comm",
              "optimizer_update", "host_gap")

# sweep priority, highest first: a slice covered by several categories is
# attributed to the first one here (host_gap is the residual, never swept)
_PRIORITY = ("exposed_comm", "optimizer_update", "backward_compute",
             "forward", "data_wait")
_PRIORITY_INDEX = {c: i for i, c in enumerate(_PRIORITY)}

# phase-span name -> lens category (tracing._PhaseSpan feeds these)
_PHASE_CATEGORY = {"kvstore": "exposed_comm", "update": "optimizer_update",
                   "bwd": "backward_compute", "fwd": "forward"}

_DEFAULT_RING = 64

_enabled_override = None
_generation = [0]       # bumped on every toggle: step windows spanning a
#                         disabled period are dropped, not booked as one
#                         giant host_gap "ghost step"


def set_enabled(flag):
    """Force the lens on/off (None = defer to GRAFT_LENS).  Toggling
    invalidates every thread's open window — the first step after a
    re-enable starts a fresh window instead of billing the whole
    disabled period to host_gap."""
    global _enabled_override
    _enabled_override = flag
    _generation[0] += 1


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    return os.environ.get("GRAFT_LENS", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def ring_size():
    try:
        n = int(os.environ.get("GRAFT_LENS_RING", str(_DEFAULT_RING)))
    except ValueError:
        return _DEFAULT_RING
    return max(n, 4)


_ring = deque(maxlen=ring_size())


def configure(size=None):
    """Re-size the step ring (keeps the newest records)."""
    global _ring
    if size is not None:
        os.environ["GRAFT_LENS_RING"] = str(int(size))
    _ring = deque(_ring, maxlen=ring_size())


class _ThreadState(object):
    """Per-thread step window: open intervals + counters.  Training loops
    are single-threaded; a second stepping thread gets its own windows
    (records from all threads share the ring)."""

    __slots__ = ("intervals", "prev_end", "completed", "io_n", "coll_n",
                 "comm_blocked", "comm_inflight", "device_s", "device_n",
                 "device_first", "gen")

    def __init__(self):
        self.intervals = []      # (category, t0, t1) in perf_counter secs
        self.prev_end = None     # previous step's end (window start)
        self.completed = 0       # steps finalized on this thread
        self.io_n = 0
        self.coll_n = 0
        self.comm_blocked = 0.0
        self.comm_inflight = 0.0
        self.device_s = 0.0      # device-busy ledger (sync-mode flushes,
        self.device_n = 0        #  serving batch dispatches)
        self.device_first = None  # earliest device span start (the first
        #                          window on a device-only thread starts
        #                          here, not at step_end)
        self.gen = _generation[0]

    def reset_window(self):
        self.intervals = []
        self.prev_end = None
        self.io_n = self.coll_n = 0
        self.comm_blocked = self.comm_inflight = 0.0
        self.device_s = 0.0
        self.device_n = 0
        self.device_first = None
        self.gen = _generation[0]


_tls = threading.local()


def _state():
    st = getattr(_tls, "lens", None)
    if st is None:
        st = _tls.lens = _ThreadState()
    elif st.gen != _generation[0]:
        st.reset_window()       # a toggle happened: the open window is
        #                         unreliable, start fresh (step ids keep
        #                         counting)
    return st


def current_step():
    """Id of the calling thread's IN-PROGRESS step window (the one the
    next ``step_end`` will finalize), or None when the lens is off or
    the thread has produced no lens activity yet.  graftwatch stamps it
    onto every flight-recorder event and tracing onto flush spans /
    collective spans — the key the cross-rank aggregator joins on."""
    if not enabled():
        return None
    st = getattr(_tls, "lens", None)
    if st is None:
        return None
    return st.completed + 1


# A loop that never crosses a step boundary (serving / evaluation — io
# and forward hooks fire, step_end never does) must not grow the open
# window without bound.  Past the cap the OLDEST intervals are dropped:
# if a step eventually closes, the early slices degrade into host_gap
# (conservation still holds); a window that large is degenerate anyway.
_MAX_OPEN_INTERVALS = 8192


def _append_interval(st, item):
    iv = st.intervals
    if len(iv) >= _MAX_OPEN_INTERVALS:
        del iv[:_MAX_OPEN_INTERVALS // 2]
    iv.append(item)


def interval(category, t0, t1):
    """Report one attributed interval (perf_counter seconds).  THE hot
    path: an env lookup, a getattr and a list append."""
    if t1 <= t0 or not enabled():
        return
    _append_interval(_state(), (category, t0, t1))


def phase(name, t0, t1):
    """One closed phase span (tracing._PhaseSpan)."""
    cat = _PHASE_CATEGORY.get(name)
    if cat is not None:
        interval(cat, t0, t1)


def io_wait(t0, t1):
    """Host blocked waiting for a data batch (io/DataLoader)."""
    if t1 <= t0 or not enabled():
        return
    st = _state()
    st.io_n += 1
    _append_interval(st, ("data_wait", t0, t1))


def comm(t0, t1, inflight=None):
    """Host blocked in one collective.  ``inflight`` is the collective's
    issue→wait-return wall time when it differs from the blocked span
    (graftlap async reduces: issued mid-backward, waited in step; an
    upper bound on issue→ready when waits queue behind each other) —
    sync collectives leave it None and the two book equal."""
    if not enabled():
        return
    st = _state()
    st.coll_n += 1
    blocked = max(t1 - t0, 0.0)
    st.comm_blocked += blocked
    st.comm_inflight += blocked if inflight is None \
        else max(float(inflight), 0.0)
    if blocked > 0.0:
        _append_interval(st, ("exposed_comm", t0, t1))


def device(t0, t1):
    """Book one DEVICE-busy span into the window's device ledger
    (ROADMAP device-time lens carry-forward, PR 8).  Three sources
    feed it: engine flushes and eager op dispatches under
    ``profiler.sync`` (both block until ready, so dispatch→ready IS
    device latency) and the serving runtime's batch dispatch
    (issue → ``block_until_ready``).  Unlike the six host components
    the device ledger is a PARALLEL decomposition: ``device_busy_s``
    vs ``device_idle_s = wall - busy`` (its own exact-sum contract),
    so comm/compute overlap is measurable on the device, not just as
    host wall."""
    if t1 <= t0 or not enabled():
        return
    st = _state()
    st.device_s += t1 - t0
    st.device_n += 1
    if st.device_first is None:
        st.device_first = t0


def _attribute(intervals, w0, w1):
    """Sweep the window once: every elementary slice goes to the
    highest-priority category covering it.  Returns (per-category
    seconds, total attributed seconds) — total <= w1 - w0 always, so
    the residual (host_gap) is non-negative by construction."""
    comp = {c: 0.0 for c in _PRIORITY}
    marks = []
    for cat, t0, t1 in intervals:
        t0 = max(t0, w0)
        t1 = min(t1, w1)
        if t1 <= t0:
            continue
        pr = _PRIORITY_INDEX[cat]
        marks.append((t0, 1, pr))
        marks.append((t1, 0, pr))    # closes sort before opens at ties
    if not marks:
        return comp, 0.0
    marks.sort()
    active = [0] * len(_PRIORITY)
    last_t = None
    total = 0.0
    for t, kind, pr in marks:
        if last_t is not None and t > last_t and any(active):
            for i, n in enumerate(active):
                if n > 0:
                    d = t - last_t
                    comp[_PRIORITY[i]] += d
                    total += d
                    break
        active[pr] += 1 if kind == 1 else -1
        last_t = t
    return comp, total


def step_end(origin="step", extra=None):
    """Finalize the calling thread's step window (called from the
    graftwatch step journal).  Returns the ring record (None when the
    lens is off)."""
    if not enabled():
        return None
    st = _state()
    now = time.perf_counter()
    w0 = st.prev_end
    if w0 is None:      # first step: window starts at the first activity
        w0 = min((t0 for _c, t0, _t1 in st.intervals), default=now)
        if st.device_first is not None:
            w0 = min(w0, st.device_first)
    wall = max(now - w0, 0.0)
    comp, attributed = _attribute(st.intervals, w0, now)
    comp["host_gap"] = max(wall - attributed, 0.0)
    st.completed += 1
    rec = {
        "step": st.completed,
        "origin": origin,
        "ended_at": time.time(),
        "wall_s": wall,
        "components": comp,
        "comm_blocked_s": st.comm_blocked,
        "comm_inflight_s": st.comm_inflight,
        "collectives": st.coll_n,
        "io_waits": st.io_n,
        "thread": threading.current_thread().name,
    }
    if st.device_n:
        # device ledger: busy + idle == wall EXACTLY (idle is wall - busy
        # by construction; busy clamps at wall — a span straddling the
        # window boundary books whole into the window it completed in)
        busy = min(st.device_s, wall)
        rec["device"] = {"busy_s": busy, "idle_s": wall - busy,
                         "spans": st.device_n}
    if extra:
        rec.update(extra)
    st.intervals = []
    st.prev_end = now
    st.io_n = st.coll_n = 0
    st.comm_blocked = st.comm_inflight = 0.0
    st.device_s = 0.0
    st.device_n = 0
    st.device_first = None
    _ring.append(rec)
    _metrics.lens_step(rec)
    _maybe_report(rec)
    return rec


def compact(rec):
    """Millisecond-rounded view of one record — what the graftwatch step
    journal embeds under its ``lens`` field."""
    out = {"wall_ms": round(rec["wall_s"] * 1e3, 3)}
    for c in COMPONENTS:
        out[c + "_ms"] = round(rec["components"][c] * 1e3, 3)
    out["comm_blocked_ms"] = round(rec["comm_blocked_s"] * 1e3, 3)
    out["comm_inflight_ms"] = round(rec["comm_inflight_s"] * 1e3, 3)
    if "device" in rec:
        out["device_busy_ms"] = round(rec["device"]["busy_s"] * 1e3, 3)
    return out


def steps():
    """The ring, oldest first (copies)."""
    return [dict(r, components=dict(r["components"])) for r in list(_ring)]


def reset():
    """Drop the ring AND the calling thread's open window (tests)."""
    _ring.clear()
    _tls.lens = None


def summary(records=None):
    """Aggregate view over the ring (or an explicit record list)."""
    recs = list(_ring) if records is None else list(records)
    if not recs:
        return {"steps": 0}
    wall = sum(r["wall_s"] for r in recs)
    comp = {c: sum(r["components"][c] for r in recs) for c in COMPONENTS}
    return {
        "steps": len(recs),
        "wall_s": wall,
        "mean_step_ms": round(wall / len(recs) * 1e3, 3),
        "components_s": {c: round(v, 6) for c, v in comp.items()},
        "fractions": {c: round(comp[c] / wall, 4) if wall > 0 else 0.0
                      for c in COMPONENTS},
        "comm_blocked_s": round(sum(r["comm_blocked_s"] for r in recs), 6),
        "comm_inflight_s": round(sum(r["comm_inflight_s"] for r in recs), 6),
    }


# ---------------------------------------------------------------------------
# GRAFT_STEP_REPORT=N: the live attribution line
# ---------------------------------------------------------------------------

def _report_every():
    try:
        return int(os.environ.get("GRAFT_STEP_REPORT", "0"))
    except ValueError:
        return 0


ABBREV = (("data_wait", "data"), ("forward", "fwd"),
           ("backward_compute", "bwd"), ("exposed_comm", "comm"),
           ("optimizer_update", "upd"), ("host_gap", "gap"))


def format_step(rec):
    parts = " ".join("%s %.2f" % (short, rec["components"][c] * 1e3)
                     for c, short in ABBREV)
    line = "graftlens step %d (%s): %.2fms | %s [ms]" % (
        rec["step"], rec["origin"], rec["wall_s"] * 1e3, parts)
    if rec["comm_inflight_s"] > rec["comm_blocked_s"]:
        line += " | comm exposed %.2f / in-flight %.2f ms" % (
            rec["comm_blocked_s"] * 1e3, rec["comm_inflight_s"] * 1e3)
    return line


def _maybe_report(rec):
    n = _report_every()
    if n <= 0 or rec["step"] % n:
        return
    lines = [format_step(rec)]
    agg = summary(list(_ring)[-n:])
    if agg.get("steps", 0) > 1:
        fr = agg["fractions"]
        lines.append(
            "graftlens last %d steps: mean %.2fms | %s" % (
                agg["steps"], agg["mean_step_ms"],
                " ".join("%s %d%%" % (short, round(fr[c] * 100))
                         for c, short in ABBREV)))
    sys.stderr.write("\n".join(lines) + "\n")
