"""graftlens — per-step wall-time attribution.

graftscope tells you what each *span* cost and graftwatch tells you what
the process was doing when it died — but neither answers the question
that drives every perf decision on this roadmap: **where did this step's
wall time go?**  (EQuARX shows collective cost dominating distributed
step time; the XLA fusion analysis shows device-time attribution is the
prerequisite to every fusion/overlap decision — both need a per-step
decomposition, not a pile of spans.)

The lens decomposes every training step's wall clock into six components
that sum EXACTLY to the step's wall time (the conservation contract,
enforced by tests/test_lens.py):

* ``data_wait``         — blocked in ``DataIter.next()`` / ``DataLoader``
                          waiting for a batch,
* ``forward``           — inside ``autograd.record()`` scopes and/or the
                          ``fwd`` phase span (Module),
* ``backward_compute``  — the ``bwd`` phase span (``autograd.backward``),
* ``exposed_comm``      — host time *visibly* spent on communication:
                          sync kvstore collective brackets,
                          ``ReduceHandle.wait`` blocks, and the trainer's
                          ``kvstore`` phase (reduce packing + waits),
* ``optimizer_update``  — the ``update`` phase span,
* ``host_gap``          — everything else (python glue, metric updates,
                          logging, user code between batches).

A *step window* runs from the end of the previous ``Trainer.step`` /
``Module.update`` journal to the end of the current one, so the data
fetch and forward of batch N land on step N — the whole loop is
attributed, not just the optimizer call.  Sources report timestamped
intervals; at step end the window is swept once and every elementary
slice is attributed to the highest-priority covering category
(``exposed_comm > optimizer_update > backward_compute > forward >
data_wait``), so overlapping instrumentation (a collective bracket
inside the kvstore phase, a record scope around a fwd span) can never
double-count.  ``host_gap`` is the residual — the six components sum to
the window by construction.

Separately from the swept component, every step carries
``comm_blocked_s`` (host time blocked in collectives) and
``comm_inflight_s`` (summed issue→wait-return wall time of the same
collectives — an upper bound on issue→ready, the same convention as
graftlap's ``graft_trainer_overlap_ratio``).  On the serial reduce path
the two are EQUAL by construction; under graftlap overlap
``comm_blocked_s < comm_inflight_s`` — the difference bounds the
communication hidden under backward.

Steps live in an in-process ring of the last ``GRAFT_LENS_RING``
(default 64) records, are published as ``graft_lens_*``
gauges/histograms, are folded into the graftwatch step journal (the
``lens`` field of ``step`` ring events), and — with
``GRAFT_STEP_REPORT=N`` — print a one-line attribution report to stderr
every N steps.  ``python -m incubator_mxnet_tpu.telemetry --steps``
renders the ring; ``--analyze`` (telemetry/aggregate.py) merges
per-rank artifacts into one cross-rank trace with straggler analytics.

Master switch: ``GRAFT_LENS`` (default on; ``set_enabled`` overrides).
The hot path per source event is one ``perf_counter`` + one list append;
``lens_overhead_pct`` in ``bench_eager.py`` keeps the cost under the 2%
bar.

graftpulse (PR 12) — the ASYNC device-time ledger: PR 11's device
ledger filled only under profiler sync mode (every dispatch blocked
until ready, so dispatch→return WAS device latency) and serving
dispatches; ordinary production async train loops — the whole point of
the engine's deferred dispatch — left it empty.  Now every engine flush
and eager op dispatch that is NOT sync-booked hands its result arrays
to a 1-thread REAPER (``device_async``): the reaper calls
``jax.block_until_ready`` OFF the caller thread and books
dispatch→device-done into the issuing thread's window.  Bookings merge
through a per-window watermark (the union of spans, never their sum),
so concurrent in-flight dispatches cannot overcount and sync-mode
bookings plus callbacks can never double-book the same span.  The
ledger keeps its exact-sum contract — ``device_busy_s + device_idle_s
== wall`` per window, busy clamped at wall — and ``busy`` is an upper
bound on true device time when the reaper queue backs up (a span's
"done" is observed at reap time).  Switch: ``GRAFT_PULSE`` (default
on; ``set_pulse`` overrides); ``pulse_overhead_pct`` in bench_eager.py
keeps the enqueue cost under the 2% bar.  For runs where callbacks are
unavailable, ``telemetry --ingest-xla PATH`` (telemetry/aggregate.py)
rebuilds the same per-step ledger offline from a chrome trace.

graftpulse — the MEMORY timeline: the step journal's single
device-mem highwater becomes a per-site allocation watermark ledger.
``mem_sample(site)`` reads the device allocator counters (cheap;
auto-disabled after the first sample on backends that report none —
set a sampler explicitly to override) at engine flush boundaries and
per fused/duplex bucket, feeding ``graft_mem_peak_bytes{site}`` /
``graft_mem_bytes_in_use``, a global timeline ring
(``mem_timeline()``/``mem_summary()``), and a per-step ``mem`` field
(peak + per-site peaks within the window) — the signal the ROADMAP's
liveness-aware memory planner will plan against.  Switch:
``GRAFT_MEM_TIMELINE`` (default on).

``add_observer(fn)`` registers a step observer called with every
finalized record — telemetry/autotune.py's controller closes the loop
from these signals back into DataLoader workers / bucket bytes /
bucket order.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = ["enabled", "set_enabled", "ring_size", "configure", "interval",
           "phase", "io_wait", "comm", "device", "step_end", "current_step",
           "steps", "summary", "compact", "reset", "COMPONENTS", "ABBREV",
           "pulse_enabled", "set_pulse", "pulse_active", "device_async",
           "pulse_drain", "pulse_stats", "mem_enabled", "set_mem_sampler",
           "mem_sample", "mem_timeline", "mem_summary", "live_arrays_sampler",
           "add_observer", "remove_observer"]

COMPONENTS = ("data_wait", "forward", "backward_compute", "exposed_comm",
              "optimizer_update", "host_gap")

# sweep priority, highest first: a slice covered by several categories is
# attributed to the first one here (host_gap is the residual, never swept)
_PRIORITY = ("exposed_comm", "optimizer_update", "backward_compute",
             "forward", "data_wait")
_PRIORITY_INDEX = {c: i for i, c in enumerate(_PRIORITY)}

# phase-span name -> lens category (tracing._PhaseSpan feeds these)
_PHASE_CATEGORY = {"kvstore": "exposed_comm", "update": "optimizer_update",
                   "bwd": "backward_compute", "fwd": "forward"}

_DEFAULT_RING = 64

_enabled_override = None
_generation = [0]       # bumped on every toggle: step windows spanning a
#                         disabled period are dropped, not booked as one
#                         giant host_gap "ghost step"


def set_enabled(flag):
    """Force the lens on/off (None = defer to GRAFT_LENS).  Toggling
    invalidates every thread's open window — the first step after a
    re-enable starts a fresh window instead of billing the whole
    disabled period to host_gap."""
    global _enabled_override
    _enabled_override = flag
    _generation[0] += 1


_OFF_VALUES = ("0", "false", "no", "off")
_lens_env_memo = ["\x00", True]     # raw value -> parsed (both flags sit
_pulse_env_memo = ["\x00", True]    # on EVERY eager dispatch: memoize the
#                                     strip/lower/member parse, keyed on
#                                     the raw string so setting the env
#                                     var mid-process still takes effect)


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    raw = os.environ.get("GRAFT_LENS", "1")
    if raw != _lens_env_memo[0]:
        _lens_env_memo[1] = raw.strip().lower() not in _OFF_VALUES
        _lens_env_memo[0] = raw
    return _lens_env_memo[1]


def ring_size():
    try:
        n = int(os.environ.get("GRAFT_LENS_RING", str(_DEFAULT_RING)))
    except ValueError:
        return _DEFAULT_RING
    return max(n, 4)


_ring = deque(maxlen=ring_size())


def configure(size=None):
    """Re-size the step ring (keeps the newest records)."""
    global _ring
    if size is not None:
        os.environ["GRAFT_LENS_RING"] = str(int(size))
    _ring = deque(_ring, maxlen=ring_size())


class _ThreadState(object):
    """Per-thread step window: open intervals + counters.  Training loops
    are single-threaded; a second stepping thread gets its own windows
    (records from all threads share the ring)."""

    __slots__ = ("intervals", "prev_end", "completed", "io_n", "coll_n",
                 "comm_blocked", "comm_inflight", "device_s", "device_n",
                 "device_first", "device_mark", "mem_peak", "mem_in_use",
                 "mem_alloc_peak", "mem_sites", "gen", "__weakref__")

    def __init__(self):
        self.intervals = []      # (category, t0, t1) in perf_counter secs
        self.prev_end = None     # previous step's end (window start)
        self.completed = 0       # steps finalized on this thread
        self.io_n = 0
        self.coll_n = 0
        self.comm_blocked = 0.0
        self.comm_inflight = 0.0
        self.device_s = 0.0      # device-busy ledger (sync-mode flushes,
        self.device_n = 0        #  serving batch dispatches, and the
        #                          async pulse reaper's done-callbacks)
        self.device_first = None  # earliest device span start (the first
        #                          window on a device-only thread starts
        #                          here, not at step_end)
        self.device_mark = None  # union watermark: end of the last booked
        #                          device span — overlapping spans (async
        #                          in-flight pipelining, sync+callback
        #                          double delivery) book only their part
        #                          past the mark, so busy is the UNION of
        #                          spans, never their sum
        self.mem_peak = 0        # window-local live-bytes watermark
        self.mem_in_use = 0
        self.mem_alloc_peak = 0  # allocator's lifetime peak as sampled
        self.mem_sites = {}      # site -> live-bytes mark in the window
        self.gen = _generation[0]

    def reset_window(self):
        self.intervals = []
        self.prev_end = None
        self.io_n = self.coll_n = 0
        self.comm_blocked = self.comm_inflight = 0.0
        self.device_s = 0.0
        self.device_n = 0
        self.device_first = None
        # device_mark survives: it is an absolute perf_counter instant
        # (span-union bookkeeping), not window state
        self.mem_peak = 0
        self.mem_in_use = 0
        self.mem_alloc_peak = 0
        self.mem_sites = {}
        self.gen = _generation[0]


_tls = threading.local()


def _state():
    st = getattr(_tls, "lens", None)
    if st is None:
        st = _tls.lens = _ThreadState()
    elif st.gen != _generation[0]:
        st.reset_window()       # a toggle happened: the open window is
        #                         unreliable, start fresh (step ids keep
        #                         counting)
    return st


def current_step():
    """Id of the calling thread's IN-PROGRESS step window (the one the
    next ``step_end`` will finalize), or None when the lens is off or
    the thread has produced no lens activity yet.  graftwatch stamps it
    onto every flight-recorder event and tracing onto flush spans /
    collective spans — the key the cross-rank aggregator joins on."""
    if not enabled():
        return None
    st = getattr(_tls, "lens", None)
    if st is None:
        return None
    return st.completed + 1


# A loop that never crosses a step boundary (serving / evaluation — io
# and forward hooks fire, step_end never does) must not grow the open
# window without bound.  Past the cap the OLDEST intervals are dropped:
# if a step eventually closes, the early slices degrade into host_gap
# (conservation still holds); a window that large is degenerate anyway.
_MAX_OPEN_INTERVALS = 8192


def _append_interval(st, item):
    iv = st.intervals
    if len(iv) >= _MAX_OPEN_INTERVALS:
        del iv[:_MAX_OPEN_INTERVALS // 2]
    iv.append(item)


def interval(category, t0, t1):
    """Report one attributed interval (perf_counter seconds).  THE hot
    path: an env lookup, a getattr and a list append."""
    if t1 <= t0 or not enabled():
        return
    _append_interval(_state(), (category, t0, t1))


def phase(name, t0, t1):
    """One closed phase span (tracing._PhaseSpan)."""
    cat = _PHASE_CATEGORY.get(name)
    if cat is not None:
        interval(cat, t0, t1)


def io_wait(t0, t1):
    """Host blocked waiting for a data batch (io/DataLoader)."""
    if t1 <= t0 or not enabled():
        return
    st = _state()
    st.io_n += 1
    _append_interval(st, ("data_wait", t0, t1))


def comm(t0, t1, inflight=None):
    """Host blocked in one collective.  ``inflight`` is the collective's
    issue→wait-return wall time when it differs from the blocked span
    (graftlap async reduces: issued mid-backward, waited in step; an
    upper bound on issue→ready when waits queue behind each other) —
    sync collectives leave it None and the two book equal."""
    if not enabled():
        return
    st = _state()
    st.coll_n += 1
    blocked = max(t1 - t0, 0.0)
    st.comm_blocked += blocked
    st.comm_inflight += blocked if inflight is None \
        else max(float(inflight), 0.0)
    if blocked > 0.0:
        _append_interval(st, ("exposed_comm", t0, t1))


# One lock guards every thread-state's device/mem ledger fields: the
# pulse reaper books into FOREIGN thread states (the issuing thread's),
# and step_end reads-and-resets the same fields.  Taken once per flush /
# step / sample — never per op record — so contention is negligible.
_device_lock = threading.Lock()


def _book_device_locked(st, t0, t1):
    """Merge one device span into ``st``'s ledger (call under
    ``_device_lock``): only the part past the union watermark books, so
    overlapping spans — pipelined async dispatches, a sync booking plus
    a late callback for the same results — count once."""
    if st.device_mark is not None and t0 < st.device_mark:
        t0 = st.device_mark
    if t1 <= t0:
        return
    st.device_s += t1 - t0
    st.device_n += 1
    st.device_mark = t1
    if st.device_first is None:
        st.device_first = t0


def device(t0, t1):
    """Book one DEVICE-busy span into the window's device ledger
    (ROADMAP device-time lens carry-forward, PR 8).  Sources: engine
    flushes and eager op dispatches under ``profiler.sync`` (both block
    until ready, so dispatch→ready IS device latency), the serving
    runtime's batch dispatch (issue → ``block_until_ready``), and —
    PR 12 — the async pulse reaper's done-callbacks (``device_async``).
    Unlike the six host components the device ledger is a PARALLEL
    decomposition: ``device_busy_s`` vs ``device_idle_s = wall - busy``
    (its own exact-sum contract), so comm/compute overlap is measurable
    on the device, not just as host wall.  Spans merge through a
    watermark (union, not sum) so no source pair can double-book."""
    if t1 <= t0 or not enabled():
        return
    st = _state()
    with _device_lock:
        _book_device_locked(st, t0, t1)


# ---------------------------------------------------------------------------
# graftpulse: the async device-time reaper (GRAFT_PULSE)
# ---------------------------------------------------------------------------

_pulse_override = None


def set_pulse(flag):
    """Force the async device ledger on/off (None = defer to
    GRAFT_PULSE)."""
    global _pulse_override
    _pulse_override = flag


def pulse_enabled():
    if _pulse_override is not None:
        return bool(_pulse_override)
    raw = os.environ.get("GRAFT_PULSE", "1")
    if raw != _pulse_env_memo[0]:
        _pulse_env_memo[1] = raw.strip().lower() not in _OFF_VALUES
        _pulse_env_memo[0] = raw
    return _pulse_env_memo[1]


def pulse_active():
    """The dispatch-site gate: both the lens and the pulse ledger on."""
    return pulse_enabled() and enabled()


_pulse_queue = deque()          # (state, gen, t_dispatch, values)
_pulse_wake = threading.Event()
_pulse_thread = [None]
_pulse_idle = threading.Condition()
_pulse_busy = [False]           # reaper mid-item (toggled under _idle)
_pulse_counts = {"enqueued": 0, "booked": 0, "dropped": 0}
_PULSE_WAKE_INTERVAL_S = 0.02   # min gap between caller-side wakes
#                                 (measured knee: shorter gaps pay one
#                                 GIL handoff per wake, longer ones pile
#                                 the whole backlog onto the drain)
_pulse_last_wake = [0.0]


def _reaper_loop():
    import jax
    while True:
        items = None
        with _pulse_idle:
            # batch-pop-and-mark-busy is atomic vs pulse_drain: the
            # queue can never look empty while items are mid-reap
            if _pulse_queue:
                items = [_pulse_queue.popleft()
                         for _ in range(len(_pulse_queue))]
                _pulse_busy[0] = True
            else:
                _pulse_busy[0] = False
                _pulse_idle.notify_all()
        if not items:
            _pulse_wake.wait(0.2)
            _pulse_wake.clear()
            continue
        # Group the batch per issuing thread-state: one thread's
        # dispatches execute device-ordered, so the LAST result's
        # readiness covers its whole group (one leaf-walk instead of
        # N — per-item ready-waits and bookings made the reaper a
        # GIL-contending metronome, the dominant ledger cost).  All
        # group spans share the batch t1, so their union is exactly
        # min(t0) -> t1: ONE merged booking per group, identical to
        # what N per-item bookings would have produced.
        groups = {}
        for it in items:
            groups.setdefault(id(it[0]), []).append(it)
        good_groups = []
        for its in groups.values():
            try:
                jax.block_until_ready(its[-1][3])
                good_groups.append(its)
            except Exception:
                # salvage per item: one failed dispatch (it surfaces on
                # the caller's read path) must not drop the whole group
                ok = []
                for it in its:
                    try:
                        jax.block_until_ready(it[3])
                        ok.append(it)
                    except Exception:
                        _pulse_counts["dropped"] += 1
                if ok:
                    good_groups.append(ok)
        t1 = time.perf_counter()
        with _device_lock:
            lens_on = enabled()
            for its in good_groups:
                st = its[0][0]
                live = [it for it in its if it[0].gen == it[1]] \
                    if lens_on else []
                _pulse_counts["dropped"] += len(its) - len(live)
                #                             (lens toggled mid-flight:
                #                              those windows are gone)
                if not live:
                    continue
                before = st.device_n
                _book_device_locked(st, min(it[2] for it in live), t1)
                if st.device_n > before:
                    # spans count real dispatches, not merged bookings
                    st.device_n += len(live) - 1
                _pulse_counts["booked"] += len(live)
        # drop every reference to the batch's result arrays BEFORE the
        # next park: locals surviving into the 0.2s idle wait would pin
        # dead buffers and make live-arrays memory accounting flicker
        st = it = its = ok = live = items = groups = good_groups = None


_pulse_spawn_lock = threading.Lock()


def _ensure_reaper():
    t = _pulse_thread[0]
    if t is not None and t.is_alive():
        return      # the hot-path fast exit: no lock once one is live
    with _pulse_spawn_lock:
        # re-check under the lock: two threads' FIRST concurrent
        # enqueues both see no live reaper — unserialized, each would
        # spawn one, and two loops fighting over _pulse_busy let
        # pulse_drain return while the loser still holds unbooked spans
        t = _pulse_thread[0]
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=_reaper_loop,
                             name="graft-pulse-reaper", daemon=True)
        _pulse_thread[0] = t
        t.start()


def device_async(values, t_dispatch):
    """Register a done-callback for one async dispatch's result arrays:
    the 1-thread reaper blocks-until-ready OFF the caller thread and
    books dispatch→device-done into THIS thread's window (captured
    here).  The caller-side cost is one deque append + an event set —
    lock-free, never a wait (the GIL orders the append; the counters
    are stats, not synchronization).  Holding ``values`` until reaped
    delays their buffers' release by the reap latency; the reaper runs
    on a ~``_PULSE_WAKE_INTERVAL_S`` cadence under traffic, so the
    overhang — and the booking delay — is up to one wake interval.
    Windows shorter than the cadence may therefore batch several
    steps' device spans into one window (each still conserving);
    ``pulse_drain()`` forces settlement where freshness matters."""
    if values is None or not pulse_active():
        return
    st = _state()
    _pulse_counts["enqueued"] += 1
    _pulse_queue.append((st, st.gen, t_dispatch, values))
    _ensure_reaper()    # full is_alive check: a fork's child inherits a
    #                     non-None dead thread — skipping the check there
    #                     would pin every result buffer ever enqueued
    if t_dispatch - _pulse_last_wake[0] > _PULSE_WAKE_INTERVAL_S \
            and not _pulse_wake.is_set():
        # RATE-LIMITED wake: waking the reaper per dispatch made it a
        # GIL-contending metronome (one thread handoff per op — the
        # dominant ledger cost, measured); dispatches between wakes
        # coalesce into one batch pop.  The 0.2s reaper poll and
        # pulse_drain's explicit kick are the backstop, so a skipped
        # wake delays a booking, never loses it.
        _pulse_last_wake[0] = t_dispatch
        _pulse_wake.set()


def pulse_drain(timeout=10.0):
    """Block until every enqueued callback has been reaped (tests, step
    benchmarks, end-of-run reports).  Returns True when drained."""
    deadline = time.monotonic() + timeout
    if _pulse_queue or _pulse_busy[0]:
        # full check: revives a dead reaper too.  The busy flag alone
        # can be latched True with an EMPTY queue — a fork mid-batch
        # gives the child a dead thread and no live reaper to clear it
        # — and only a fresh reaper's first empty pop resets it; gating
        # on the queue alone would burn the whole timeout
        _ensure_reaper()
    with _pulse_idle:
        while _pulse_queue or _pulse_busy[0]:
            _pulse_wake.set()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _pulse_idle.wait(min(remaining, 0.05))
    return True


def pulse_stats():
    """{"enqueued", "booked", "dropped", "pending"} — reaper counters
    (tests; the no-double-booking contract asserts enqueued == 0 under
    sync mode)."""
    return dict(_pulse_counts,
                pending=len(_pulse_queue) + (1 if _pulse_busy[0] else 0))


def reset_pulse_stats():
    for k in _pulse_counts:
        _pulse_counts[k] = 0


# ---------------------------------------------------------------------------
# graftpulse: the per-site memory timeline (GRAFT_MEM_TIMELINE)
# ---------------------------------------------------------------------------

_MEM_RING_SIZE = 512
_mem_ring = deque(maxlen=_MEM_RING_SIZE)    # {"t","site","in_use","peak"}
_mem_sampler = [None]       # explicit override (tests / --mem demo)
_mem_auto_dead = [False]    # default sampler found no allocator stats


_mem_env_memo = ["\x00", True]  # same raw-keyed memo as GRAFT_LENS/_PULSE:
#                                 this flag sits on every flush boundary
#                                 and every fused/duplex bucket apply


def mem_enabled():
    raw = os.environ.get("GRAFT_MEM_TIMELINE", "1")
    if raw != _mem_env_memo[0]:
        _mem_env_memo[1] = raw.strip().lower() not in _OFF_VALUES
        _mem_env_memo[0] = raw
    return _mem_env_memo[1]


def set_mem_sampler(fn):
    """Install a sampler ``fn() -> (bytes_in_use, peak_bytes) | None``
    (None = revert to the allocator-counter default).  Re-arms the
    auto-disable latch."""
    _mem_sampler[0] = fn
    _mem_auto_dead[0] = False


def _allocator_sampler():
    """Allocator counters summed over local devices — the cheap default
    (real TPU/GPU runtimes).  Returns None when no device reports any
    (host CPU): the caller then latches the ledger off, so backends
    without counters pay one probe total, not one per flush."""
    try:
        import jax
        in_use = peak = 0
        found = False
        for d in jax.local_devices():
            s = d.memory_stats() or {}
            if s:
                in_use += int(s.get("bytes_in_use", 0))
                peak += int(s.get("peak_bytes_in_use", 0))
                found = True
        return (in_use, peak) if found else None
    except Exception:
        return None


def live_arrays_sampler():
    """Exact live bytes via ``profiler.device_memory()``'s live-arrays
    walk — too slow for per-flush production sampling, right for the
    ``--mem`` CLI demo and tests on allocator-less backends."""
    from .. import profiler as _profiler
    ms = _profiler.device_memory()
    return (sum(m["bytes_in_use"] for m in ms),
            sum(m["peak_bytes_in_use"] for m in ms))


def mem_sample(site):
    """Sample the device-memory watermark at one attribution site (an
    engine flush boundary, a fused/duplex bucket, a serving batch) into
    the timeline ring, the calling thread's step window and the
    ``graft_mem_peak_bytes{site}`` gauges."""
    if not enabled() or not mem_enabled():
        return None
    fn = _mem_sampler[0]
    if fn is None:
        if _mem_auto_dead[0]:
            return None
        fn = _allocator_sampler
    sample = None
    try:
        sample = fn()
    except Exception:
        sample = None
    if sample is None:
        if _mem_sampler[0] is None:
            _mem_auto_dead[0] = True
        return None
    in_use, peak = int(sample[0]), int(sample[1])
    peak = max(peak, in_use)
    st = _state()
    with _device_lock:
        st.mem_in_use = in_use
        # attribution is by LIVE bytes at the site boundary: the
        # allocator's peak counter is a process-lifetime high-water mark
        # (never resets), so keying sites off it would tie every site to
        # one constant once the global peak is first reached — in_use is
        # what differentiates which bucket/flush drives the footprint.
        # The raw allocator peak rides along separately (alloc_peak): it
        # bounds spikes BETWEEN samples that in_use snapshots miss
        st.mem_peak = max(st.mem_peak, in_use)
        st.mem_alloc_peak = max(st.mem_alloc_peak, peak)
        site_mark = max(st.mem_sites.get(site, 0), in_use)
        st.mem_sites[site] = site_mark
    _mem_ring.append({"t": time.time(), "site": site,
                      "in_use": in_use, "peak": peak})
    _metrics.mem_sample(site, in_use, site_mark)
    return in_use, peak


def mem_timeline():
    """The memory timeline ring, oldest first (copies)."""
    return [dict(r) for r in list(_mem_ring)]


def mem_summary():
    """Per-site aggregation over the ring: samples, live-bytes watermark
    (what differentiates sites — the allocator peak is lifetime-
    cumulative and ties them), raw allocator peak, last in-use."""
    out = {}
    for r in list(_mem_ring):
        s = out.setdefault(r["site"], {"samples": 0, "peak_bytes": 0,
                                       "alloc_peak_bytes": 0,
                                       "last_in_use": 0})
        s["samples"] += 1
        s["peak_bytes"] = max(s["peak_bytes"], r["in_use"])
        s["alloc_peak_bytes"] = max(s["alloc_peak_bytes"], r["peak"])
        s["last_in_use"] = r["in_use"]
    return out


def reset_mem():
    _mem_ring.clear()
    _mem_auto_dead[0] = False


# ---------------------------------------------------------------------------
# step observers (the autotuner's feed)
# ---------------------------------------------------------------------------

_observers = []


def add_observer(fn):
    """Register ``fn(record)`` to run after every finalized step window
    (telemetry/autotune.py's controller).  Idempotent."""
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn):
    try:
        _observers.remove(fn)
    except ValueError:
        pass


def _notify_observers(rec):
    for fn in list(_observers):
        try:
            fn(rec)
        except Exception:
            import logging
            logging.getLogger("graftlens").exception(
                "lens step observer %r raised", fn)


def _attribute(intervals, w0, w1):
    """Sweep the window once: every elementary slice goes to the
    highest-priority category covering it.  Returns (per-category
    seconds, total attributed seconds) — total <= w1 - w0 always, so
    the residual (host_gap) is non-negative by construction."""
    comp = {c: 0.0 for c in _PRIORITY}
    marks = []
    for cat, t0, t1 in intervals:
        t0 = max(t0, w0)
        t1 = min(t1, w1)
        if t1 <= t0:
            continue
        pr = _PRIORITY_INDEX[cat]
        marks.append((t0, 1, pr))
        marks.append((t1, 0, pr))    # closes sort before opens at ties
    if not marks:
        return comp, 0.0
    marks.sort()
    active = [0] * len(_PRIORITY)
    last_t = None
    total = 0.0
    for t, kind, pr in marks:
        if last_t is not None and t > last_t and any(active):
            for i, n in enumerate(active):
                if n > 0:
                    d = t - last_t
                    comp[_PRIORITY[i]] += d
                    total += d
                    break
        active[pr] += 1 if kind == 1 else -1
        last_t = t
    return comp, total


def step_end(origin="step", extra=None):
    """Finalize the calling thread's step window (called from the
    graftwatch step journal).  Returns the ring record (None when the
    lens is off)."""
    if not enabled():
        return None
    st = _state()
    now = time.perf_counter()
    w0 = st.prev_end
    # device/mem ledger fields are shared with the pulse reaper thread:
    # snapshot-and-reset them under the lock so a callback landing mid-
    # finalize books entirely into this window or entirely into the next
    with _device_lock:
        device_s, device_n = st.device_s, st.device_n
        device_first = st.device_first
        mem_peak, mem_in_use = st.mem_peak, st.mem_in_use
        mem_alloc_peak = st.mem_alloc_peak
        mem_sites = st.mem_sites
        st.device_s = 0.0
        st.device_n = 0
        st.device_first = None
        st.mem_peak = 0
        st.mem_in_use = 0
        st.mem_alloc_peak = 0
        st.mem_sites = {}
    if w0 is None:      # first step: window starts at the first activity
        w0 = min((t0 for _c, t0, _t1 in st.intervals), default=now)
        if device_first is not None:
            w0 = min(w0, device_first)
    wall = max(now - w0, 0.0)
    comp, attributed = _attribute(st.intervals, w0, now)
    comp["host_gap"] = max(wall - attributed, 0.0)
    st.completed += 1
    rec = {
        "step": st.completed,
        "origin": origin,
        "ended_at": time.time(),
        "wall_s": wall,
        "components": comp,
        "comm_blocked_s": st.comm_blocked,
        "comm_inflight_s": st.comm_inflight,
        "collectives": st.coll_n,
        "io_waits": st.io_n,
        "thread": threading.current_thread().name,
    }
    if device_n:
        # device ledger: busy + idle == wall EXACTLY (idle is wall - busy
        # by construction; busy clamps at wall — a span straddling the
        # window boundary books whole into the window it completed in)
        busy = min(device_s, wall)
        rec["device"] = {"busy_s": busy, "idle_s": wall - busy,
                         "spans": device_n}
    if mem_sites:
        # peak_bytes is the window's LIVE-bytes watermark (== max over
        # sites by construction — the attribution conservation); the raw
        # allocator peak (a lifetime high-water mark) rides along for
        # spikes between samples
        rec["mem"] = {"peak_bytes": mem_peak, "in_use_bytes": mem_in_use,
                      "alloc_peak_bytes": mem_alloc_peak,
                      "sites": mem_sites}
    if extra:
        rec.update(extra)
    st.intervals = []
    st.prev_end = now
    st.io_n = st.coll_n = 0
    st.comm_blocked = st.comm_inflight = 0.0
    _ring.append(rec)
    _metrics.lens_step(rec)
    _maybe_report(rec)
    _notify_observers(rec)
    return rec


def compact(rec):
    """Millisecond-rounded view of one record — what the graftwatch step
    journal embeds under its ``lens`` field."""
    out = {"wall_ms": round(rec["wall_s"] * 1e3, 3)}
    for c in COMPONENTS:
        out[c + "_ms"] = round(rec["components"][c] * 1e3, 3)
    out["comm_blocked_ms"] = round(rec["comm_blocked_s"] * 1e3, 3)
    out["comm_inflight_ms"] = round(rec["comm_inflight_s"] * 1e3, 3)
    if "device" in rec:
        out["device_busy_ms"] = round(rec["device"]["busy_s"] * 1e3, 3)
    if "mem" in rec:
        out["mem_peak_bytes"] = rec["mem"]["peak_bytes"]
    if rec.get("compiled"):
        # graftstep: a whole-step compiled window — one donated XLA
        # program booked as a single device span; flagged so step rings
        # distinguish compiled from bucketed-eager windows at a glance
        out["compiled"] = True
    return out


def attach_xray(summary, max_records=None):
    """graftxray feed: annotate the most recent COMPILED ring records
    (newest first) with a capture session's device-side attribution —
    the real device span (``span`` t0/t1 in the trace timebase, the
    per-step device share) and the per-phase device seconds that the
    host-observed single ``device_async`` span of compiled mode cannot
    resolve.  Additive only (a new ``xray`` key): the window's
    host-side six-component conservation is untouched.  Returns the
    number of records annotated."""
    n = 0
    for rec in reversed(_ring):
        if max_records is not None and n >= max_records:
            break
        if not rec.get("compiled") or "xray" in rec:
            continue
        rec["xray"] = dict(summary)
        n += 1
    return n


def steps():
    """The ring, oldest first (copies)."""
    return [dict(r, components=dict(r["components"])) for r in list(_ring)]


def reset():
    """Drop the ring AND the calling thread's open window (tests)."""
    _ring.clear()
    _mem_ring.clear()
    _tls.lens = None


def summary(records=None):
    """Aggregate view over the ring (or an explicit record list)."""
    recs = list(_ring) if records is None else list(records)
    if not recs:
        return {"steps": 0}
    wall = sum(r["wall_s"] for r in recs)
    comp = {c: sum(r["components"][c] for r in recs) for c in COMPONENTS}
    return {
        "steps": len(recs),
        "wall_s": wall,
        "mean_step_ms": round(wall / len(recs) * 1e3, 3),
        "components_s": {c: round(v, 6) for c, v in comp.items()},
        "fractions": {c: round(comp[c] / wall, 4) if wall > 0 else 0.0
                      for c in COMPONENTS},
        "comm_blocked_s": round(sum(r["comm_blocked_s"] for r in recs), 6),
        "comm_inflight_s": round(sum(r["comm_inflight_s"] for r in recs), 6),
    }


# ---------------------------------------------------------------------------
# GRAFT_STEP_REPORT=N: the live attribution line
# ---------------------------------------------------------------------------

def _report_every():
    try:
        return int(os.environ.get("GRAFT_STEP_REPORT", "0"))
    except ValueError:
        return 0


ABBREV = (("data_wait", "data"), ("forward", "fwd"),
           ("backward_compute", "bwd"), ("exposed_comm", "comm"),
           ("optimizer_update", "upd"), ("host_gap", "gap"))


def format_step(rec):
    parts = " ".join("%s %.2f" % (short, rec["components"][c] * 1e3)
                     for c, short in ABBREV)
    line = "graftlens step %d (%s): %.2fms | %s [ms]" % (
        rec["step"], rec["origin"], rec["wall_s"] * 1e3, parts)
    if rec["comm_inflight_s"] > rec["comm_blocked_s"]:
        line += " | comm exposed %.2f / in-flight %.2f ms" % (
            rec["comm_blocked_s"] * 1e3, rec["comm_inflight_s"] * 1e3)
    return line


def _maybe_report(rec):
    n = _report_every()
    if n <= 0 or rec["step"] % n:
        return
    lines = [format_step(rec)]
    agg = summary(list(_ring)[-n:])
    if agg.get("steps", 0) > 1:
        fr = agg["fractions"]
        lines.append(
            "graftlens last %d steps: mean %.2fms | %s" % (
                agg["steps"], agg["mean_step_ms"],
                " ".join("%s %d%%" % (short, round(fr[c] * 100))
                         for c, short in ABBREV)))
    sys.stderr.write("\n".join(lines) + "\n")
