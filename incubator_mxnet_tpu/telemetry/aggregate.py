"""graftlens cross-rank trace aggregation + straggler analytics.

One rank's trace answers *where did my step time go* (telemetry/lens.py);
it cannot answer the second question that dominates distributed step
time (EQuARX, arXiv:2506.17615): **which rank made everyone wait?**  A
sync collective exits everywhere at once, so the rank that *entered*
last paid nothing and billed its lateness to every peer — visible only
by putting all ranks' timelines side by side.

This module merges N per-rank artifacts — chrome traces dumped by the
profiler and/or graftwatch flight-recorder dumps, mixed freely — into:

* **one merged chrome trace**: each rank is its own labeled process
  track (``process_name`` metadata), every collective/flush/step lands
  at its clock-aligned wall time, and each cross-rank collective gets a
  flow link (``s`` on the first rank to enter, ``t`` hops, ``f`` on the
  last) so the trace UI draws the arrow from the early rank into the
  straggler;
* **a straggler table**: per (step, collective): last-to-enter rank,
  last-to-exit rank, enter-spread and exit-spread seconds, plus a blame
  summary counting how often each rank entered last.

Clock alignment uses the sync points the system already has: the
piggybacked heartbeat ``(ts, step)`` samples (graftwatch, PR 6) and
SYNC collective exits matched by the SPMD-lockstep sequence number — a
sync allreduce returns everywhere at (nearly) the same instant, so the
median pairwise delta of matched anchors IS the clock offset.  Async
reduces (graftlap's ``reduce_many_async``) are excluded from anchors
and from exit stats: their recorded exit is the host-local wait-return,
not a wire instant (their issue-time *enter* remains valid straggler
evidence).  A lone dump falls back to the ``clock_offset_s`` recorded
in its header.  Note the consequence: exit spreads are measured
*around the median sync behavior*, so they surface per-collective
anomalies, while enter spreads carry the full straggler signal.

CLI: ``python -m incubator_mxnet_tpu.telemetry --analyze R0.json
R1.json [--json | --merged OUT.json]``; ``--analyze --selftest`` is the
lint smoke tier (two synthetic rank dumps with a deliberately delayed
rank → merged trace must validate, every reduced bucket must get a
cross-rank flow link, and the table must blame the delayed rank).
"""
from __future__ import annotations

import json
import os
import statistics
import tempfile

from . import tracing as _tracing

__all__ = ["load_artifact", "parse_artifact", "clock_offsets",
           "merged_trace", "straggler_table", "lockstep_check",
           "analyze", "selftest", "ingest_xla"]

_BLACKBOX_SCHEMA = "graft-blackbox/1"


# ---------------------------------------------------------------------------
# artifact loading: blackbox dumps + chrome traces → one common shape
# ---------------------------------------------------------------------------

def load_artifact(path):
    """Parse one per-rank artifact file (auto-detects the format)."""
    with open(path) as f:
        doc = json.load(f)
    return parse_artifact(doc, source=os.path.basename(path))


def parse_artifact(doc, source="<memory>"):
    """Parse an already-loaded artifact dict.  Returns the common
    artifact shape: ``{kind, source, rank, collectives, heartbeats,
    spans, events, clock_offset_s}`` with all times in wall-clock
    seconds."""
    if isinstance(doc, dict) and doc.get("schema") == _BLACKBOX_SCHEMA:
        return _parse_dump(doc, source)
    if isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        return _parse_trace(doc, source)
    raise ValueError("%s: neither a graftwatch dump (schema %r) nor a "
                     "chrome trace (traceEvents)" % (source,
                                                     _BLACKBOX_SCHEMA))


def _collective_key(data, per_path_seq):
    """Cross-rank matching key for one collective.  The lockstep ``seq``
    stamp is exact; artifacts predating it fall back to per-path
    occurrence order (still correct under the lockstep contract)."""
    seq = data.get("seq")
    if seq is not None:
        return ("seq", int(seq))
    path = data.get("path") or "collective"
    n = per_path_seq[path] = per_path_seq.get(path, 0) + 1
    return ("path", path, n)


def _parse_dump(doc, source):
    rank = doc.get("rank")
    colls, hbs, spans = [], [], []
    per_path_seq = {}
    for e in doc.get("events") or []:
        kind, data = e.get("kind"), e.get("data") or {}
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "collective":
            dur = max(float(data.get("latency_ms") or 0.0) / 1e3, 0.0)
            colls.append({
                "key": _collective_key(data, per_path_seq),
                "step": data.get("step"),
                "label": data.get("bucket") or data.get("path",
                                                        "collective"),
                "path": data.get("path"),
                "enter": ts - dur, "exit": ts,
                "nbytes": data.get("nbytes"),
                "n_keys": data.get("n_keys"),
            })
        elif kind == "dist_heartbeat":
            hbs.append({"hb": data.get("step"), "ts": ts})
        else:
            spans.append({"kind": kind, "ts": ts, "data": data})
    return {"kind": "blackbox", "source": source,
            "rank": int(rank) if rank is not None else None,
            "collectives": colls, "heartbeats": hbs, "spans": spans,
            "events": None, "anchor": None,
            "clock_offset_s": doc.get("clock_offset_s"),
            "lockstep": doc.get("lockstep")}


def _parse_trace(doc, source):
    events = doc["traceEvents"]
    other = doc.get("otherData") or {}
    rank = other.get("rank")
    if rank is None:
        for e in events:
            if isinstance(e, dict) and e.get("ph") == "M" \
                    and e.get("name") == "process_name":
                name = (e.get("args") or {}).get("name", "")
                parts = name.split()
                if len(parts) >= 2 and parts[0] == "rank":
                    try:
                        rank = int(parts[1])
                    except ValueError:
                        pass
                    break
    anchor = other.get("wall_anchor")
    wall = _wall_fn(anchor)
    colls = []
    per_path_seq = {}
    for e in events:
        if isinstance(e, dict) and e.get("ph") == "X" \
                and e.get("cat") == "collective":
            args = e.get("args") or {}
            enter = wall(e.get("ts", 0.0))
            colls.append({
                "key": _collective_key(args, per_path_seq),
                "step": args.get("step"),
                "label": args.get("bucket") or args.get("path",
                                                        e.get("name")),
                "path": args.get("path"),
                "enter": enter,
                "exit": wall(e.get("ts", 0.0) + e.get("dur", 0.0)),
                "nbytes": args.get("nbytes"),
                "n_keys": args.get("n_keys"),
            })
    return {"kind": "trace", "source": source,
            "rank": int(rank) if rank is not None else None,
            "collectives": colls, "heartbeats": [], "spans": [],
            "events": events, "anchor": anchor,
            "clock_offset_s": other.get("clock_offset_s")}


def _wall_fn(anchor):
    if anchor and "wall_s" in anchor and "perf_us" in anchor:
        wall_s, perf_us = float(anchor["wall_s"]), float(anchor["perf_us"])
        return lambda ts_us: wall_s + (ts_us - perf_us) / 1e6
    return lambda ts_us: ts_us / 1e6


def _assign_ranks(artifacts):
    """Fill missing ranks with unclaimed ints.  Several artifacts MAY
    share a rank (that rank's profiler trace AND its blackbox dump —
    'mixed freely'): they merge onto one track and their collectives
    dedup per (key, rank)."""
    claimed = {a["rank"] for a in artifacts if a["rank"] is not None}
    nxt = 0
    for a in artifacts:
        if a["rank"] is None:
            while nxt in claimed:
                nxt += 1
            a["rank"] = nxt
            claimed.add(nxt)
    return []


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

# Async reduces (graftlap) and async weight pulls (graftduplex) are
# recorded at wait-return/abandon time — a HOST-local instant, not the
# wire-synchronized exit a sync allreduce has.  They are valid
# straggler-ENTER evidence (enter = issue time) but must never serve as
# clock anchors or exit-spread evidence: a healthy 40ms host lag before
# wait() would otherwise fabricate a 40ms clock offset and blame an
# innocent rank.  Sync pull collectives (path "pull") keep full exit
# standing.  Mirror of blackbox._NO_STRAGGLER_PATHS.
_ASYNC_PATHS = frozenset(["reduce_many_async", "pull_many_async"])


def _anchors(artifact):
    out = {}
    for h in artifact["heartbeats"]:
        if h["hb"] is not None:
            out[("hb", h["hb"])] = h["ts"]
    for c in artifact["collectives"]:
        if c.get("path") not in _ASYNC_PATHS:
            out[("c",) + c["key"]] = c["exit"]
    return out


def clock_offsets(artifacts):
    """Per-rank clock offset (seconds to SUBTRACT from that rank's
    timestamps) relative to the first artifact's rank, from the median
    delta of matched sync anchors (heartbeats by step, sync collective
    exits by lockstep seq).  Artifacts sharing a rank (trace + dump of
    one process share one clock) pool their anchors."""
    anchors_by_rank, hints = {}, {}
    for a in artifacts:
        anchors_by_rank.setdefault(a["rank"], {}).update(_anchors(a))
        if a.get("clock_offset_s") is not None:
            hints.setdefault(a["rank"], float(a["clock_offset_s"]))
    ref_rank = artifacts[0]["rank"]
    ref_anchors = anchors_by_rank[ref_rank]
    out = {ref_rank: 0.0}
    for rank, mine in anchors_by_rank.items():
        if rank == ref_rank:
            continue
        deltas = [mine[k] - ref_anchors[k] for k in mine
                  if k in ref_anchors]
        if deltas:
            off = statistics.median(deltas)
        elif rank in hints and ref_rank in hints:
            off = hints[ref_rank] - hints[rank]
        else:
            off = 0.0
        out[rank] = off
    return out


# ---------------------------------------------------------------------------
# the merged trace
# ---------------------------------------------------------------------------

def _matched_collectives(artifacts):
    """key -> [(rank, collective)], one entry per (key, rank): a rank's
    trace and dump both record the same wire collective — the first
    artifact claiming a (key, rank) wins, so same-rank artifacts can
    never fake a cross-rank match against themselves."""
    by_key = {}
    seen = set()
    for a in artifacts:
        for c in a["collectives"]:
            if (c["key"], a["rank"]) in seen:
                continue
            seen.add((c["key"], a["rank"]))
            by_key.setdefault(c["key"], []).append((a["rank"], c))
    return by_key


def _min_time(a):
    times = [c["enter"] for c in a["collectives"]]
    times += [h["ts"] for h in a["heartbeats"]]
    # span events are stamped at their END; the merged X event starts at
    # ts - latency, so the time base must cover the start or rel()'s
    # clamp-to-zero would stretch the earliest span over the origin
    times += [s["ts"] - max(float(s["data"].get("latency_ms") or 0.0),
                            0.0) / 1e3
              for s in a["spans"]]
    if a["kind"] == "trace":
        wall = _wall_fn(a["anchor"])
        times += [wall(e["ts"]) for e in a["events"]
                  if isinstance(e, dict) and isinstance(e.get("ts"),
                                                        (int, float))]
    return min(times) if times else 0.0


def merged_trace(artifacts, offsets=None):
    """Build ONE chrome trace over all ranks: per-rank process tracks
    (pid = rank), clock-aligned events, and one cross-rank flow link per
    collective observed on >= 2 ranks.  Returns ``(trace_dict,
    n_cross_rank_links)``."""
    offsets = offsets if offsets is not None else clock_offsets(artifacts)
    t0 = min((_min_time(a) - offsets[a["rank"]] for a in artifacts),
             default=0.0)

    def rel(ts, rank):
        return max((ts - offsets[rank] - t0) * 1e6, 0.0)

    events = []
    labeled = set()
    for a in artifacts:
        rank = a["rank"]
        if rank not in labeled:     # one metadata set per TRACK, even
            labeled.add(rank)       # when several artifacts share it
            role = "+".join(sorted({x["kind"] for x in artifacts
                                    if x["rank"] == rank}))
            events += _tracing.process_metadata_events(
                rank=rank, role=role, pid=rank)
        if a["kind"] == "blackbox":
            events += _dump_events(a, rank, rel)
        else:
            events += _trace_events(a, rank, rel)
    links = _cross_rank_links(artifacts, offsets, rel, events)
    ranks = sorted(a["rank"] for a in artifacts)
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"merged_ranks": ranks,
                           "clock_offsets_s": {str(r): round(offsets[r], 6)
                                               for r in offsets},
                           "time_base_wall_s": t0}}
    return trace, links


def _dump_events(a, rank, rel):
    out = []
    for c in a["collectives"]:
        dur_us = max((c["exit"] - c["enter"]) * 1e6, 0.01)
        args = {"path": c["path"]}
        for k in ("step", "nbytes", "n_keys"):
            if c.get(k) is not None:
                args[k] = c[k]
        if c["key"][0] == "seq":
            args["seq"] = c["key"][1]
        out.append({"name": c["label"], "cat": "collective", "ph": "X",
                    "ts": rel(c["enter"], rank), "dur": dur_us,
                    "pid": rank, "tid": 0, "args": args})
    for s in a["spans"]:
        data, kind, ts = s["data"], s["kind"], s["ts"]
        if kind in ("engine_flush", "step"):
            dur = max(float(data.get("latency_ms") or 0.0) / 1e3, 0.0)
            name = "bulk_segment_flush" if kind == "engine_flush" \
                else "step"
            cat = "engine" if kind == "engine_flush" else "step"
            out.append({"name": name, "cat": cat, "ph": "X",
                        "ts": rel(ts - dur, rank),
                        "dur": max(dur * 1e6, 0.01),
                        "pid": rank, "tid": 0, "args": data})
        else:
            out.append({"name": kind, "cat": "blackbox", "ph": "i",
                        "ts": rel(ts, rank), "pid": rank, "tid": 0,
                        "s": "t", "args": data})
    for h in a["heartbeats"]:
        out.append({"name": "heartbeat", "cat": "dist", "ph": "i",
                    "ts": rel(h["ts"], rank), "pid": rank, "tid": 0,
                    "s": "t", "args": {"hb": h["hb"]}})
    return out


def _trace_events(a, rank, rel):
    wall = _wall_fn(a["anchor"])
    out = []
    for e in a["events"]:
        if not isinstance(e, dict):
            continue
        ph = e.get("ph")
        if ph == "M":
            continue            # replaced by the merge's own metadata
        ne = dict(e)
        ne["pid"] = rank
        if isinstance(ne.get("ts"), (int, float)):
            ne["ts"] = rel(wall(ne["ts"]), rank)
        if ph in ("s", "t", "f") and "id" in ne:
            # namespace single-rank flow ids so two ranks' segment
            # counters can never collide in the merged id space
            ne["id"] = "r%d/%s" % (rank, ne["id"])
        out.append(ne)
    return out


def _cross_rank_links(artifacts, offsets, rel, events):
    """One flow chain per collective seen on >= 2 ranks: s on the first
    rank to enter, t hops through the middle, f on the last — the arrow
    the trace UI draws INTO the straggler.  Bind points sit mid-slice so
    each hop attaches to that rank's collective span."""
    links = 0
    for key, rcs in sorted(_matched_collectives(artifacts).items(),
                           key=lambda kv: str(kv[0])):
        if len(rcs) < 2:
            continue
        rcs = sorted(rcs, key=lambda rc: rc[1]["enter"] - offsets[rc[0]])
        fid = "xr/" + "/".join(str(p) for p in key)
        for i, (rank, c) in enumerate(rcs):
            mid = rel(c["enter"], rank) \
                + max((c["exit"] - c["enter"]) * 1e6, 0.01) / 2.0
            ph = "s" if i == 0 else ("f" if i == len(rcs) - 1 else "t")
            ev = {"name": "xrank_collective", "cat": "xrank.flow",
                  "ph": ph, "id": fid, "ts": mid, "pid": rank, "tid": 0,
                  "args": {"step": c.get("step"), "label": c["label"]}}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)
        links += 1
    return links


# ---------------------------------------------------------------------------
# straggler analytics
# ---------------------------------------------------------------------------

def straggler_table(artifacts, offsets=None):
    """Per (step × collective) rows + a blame summary.  ``rows`` are in
    key order; each carries last-to-enter/exit rank and the aligned
    enter/exit spreads in seconds."""
    offsets = offsets if offsets is not None else clock_offsets(artifacts)
    rows = []
    blame = {a["rank"]: 0 for a in artifacts}
    for key, rcs in sorted(_matched_collectives(artifacts).items(),
                           key=lambda kv: str(kv[0])):
        if len(rcs) < 2:
            continue
        enters = {r: c["enter"] - offsets[r] for r, c in rcs}
        last_enter = max(enters, key=enters.get)
        step = next((c.get("step") for _r, c in rcs
                     if c.get("step") is not None), None)
        is_async = rcs[0][1].get("path") in _ASYNC_PATHS
        if is_async:
            # wait-return times are host-local: exit stats would blame
            # whichever rank's host got to wait() last, not the wire
            last_exit, exit_spread = None, None
        else:
            exits = {r: c["exit"] - offsets[r] for r, c in rcs}
            last_exit = max(exits, key=exits.get)
            exit_spread = round(max(exits.values())
                                - min(exits.values()), 6)
        rows.append({
            "key": list(key),
            "step": step,
            "label": rcs[0][1]["label"],
            "ranks": sorted(enters),
            "last_to_enter": last_enter,
            "last_to_exit": last_exit,
            "enter_spread_s": round(max(enters.values())
                                    - min(enters.values()), 6),
            "exit_spread_s": exit_spread,
        })
        blame[last_enter] = blame.get(last_enter, 0) + 1
    matched = len(rows)
    summary = {
        "collectives_matched": matched,
        "blame": {str(r): n for r, n in sorted(blame.items())},
        "worst_rank": (max(blame, key=lambda r: blame[r])
                       if matched else None),
        "max_enter_spread_s": round(max((r["enter_spread_s"]
                                         for r in rows), default=0.0), 6),
        "mean_enter_spread_s": round(
            sum(r["enter_spread_s"] for r in rows) / matched, 6)
        if matched else 0.0,
    }
    return rows, summary


# ---------------------------------------------------------------------------
# lockstep divergence cross-check (grafttsan's auditor, offline half)
# ---------------------------------------------------------------------------

# host parameter-service RPCs are rank-asymmetric by design (async SGD):
# mirror of analysis/lockstep.py EXCLUDED_PATHS
_PS_PATHS = frozenset(["ps_push", "ps_pull", "ps_push_async"])


def lockstep_check(artifacts):
    """Audit the SPMD lockstep contract across rank artifacts: for every
    collective seq observed on >= 2 ranks, the identity ``(path,
    n_keys, nbytes, label)`` must agree — a mismatch names the exact
    divergent collective the online rolling hash (analysis/lockstep.py)
    could only bound.  Holes — a rank missing a seq inside its observed
    range while peers have it — catch skipped collectives.  Any online
    ``lockstep_divergence`` reports recorded in the dumps are surfaced
    too."""
    ranks = sorted({a["rank"] for a in artifacts})
    # a ps_* bracket consumes the shared seq counter at rank-dependent
    # timing (the dist_async background client), so on a ps-bearing
    # artifact set seq N on one rank is simply a DIFFERENT collective
    # than seq N on another — seq matching would blame healthy ranks.
    # The lockstep contract is a sync-wire contract; decline the audit
    # for async-wire sets (the online fold-index hash still covers them).
    has_ps = any(c.get("path") in _PS_PATHS
                 for a in artifacts for c in a["collectives"])
    by_seq = {}
    if not has_ps:
        for key, rcs in _matched_collectives(artifacts).items():
            if key[0] != "seq":
                continue
            sigs = {r: (c.get("path"), c.get("n_keys"), c.get("nbytes"),
                        c.get("label"))
                    for r, c in rcs}
            if sigs:
                by_seq[key[1]] = sigs
    mismatches, holes = [], []
    seq_range = {}              # rank -> (min seq, max seq) observed
    for seq, sigs in by_seq.items():
        for r in sigs:
            lo, hi = seq_range.get(r, (seq, seq))
            seq_range[r] = (min(lo, seq), max(hi, seq))
    for seq in sorted(by_seq):
        sigs = by_seq[seq]
        if len(set(sigs.values())) > 1:
            counts = {}
            for v in sigs.values():
                counts[v] = counts.get(v, 0) + 1
            majority = max(counts, key=counts.get)
            mismatches.append({
                "seq": seq,
                "per_rank": {str(r): list(v)
                             for r, v in sorted(sigs.items())},
                "divergent_ranks": sorted(r for r, v in sigs.items()
                                          if v != majority),
            })
        for r, (lo, hi) in seq_range.items():
            # only a hole INSIDE the rank's own observed range is
            # evidence (ring eviction trims the edges legitimately)
            if r not in sigs and lo < seq < hi:
                holes.append({"seq": seq, "missing_rank": r})
    online = []
    for a in artifacts:
        for s in a["spans"]:
            if s["kind"] == "lockstep_divergence":
                online.append(dict(s["data"], rank=a["rank"]))
        ls = a.get("lockstep") or {}
        if ls.get("divergence"):
            online.append(dict(ls["divergence"], rank=a["rank"],
                               source="dump-header"))
    bad_seqs = [m["seq"] for m in mismatches] + [h["seq"] for h in holes]
    divergent = sorted({r for m in mismatches
                        for r in m["divergent_ranks"]}
                       | {h["missing_rank"] for h in holes})
    report = {
        "seqs_checked": len(by_seq),
        "ranks": ranks,
        "first_divergent_seq": min(bad_seqs) if bad_seqs else None,
        "divergent_ranks": divergent,
        "mismatches": mismatches[:10],
        "holes": holes[:10],
        "online_reports": online[:10],
    }
    if has_ps:
        report["note"] = ("async wire (ps_* collectives present): seq "
                          "matching skipped — wire seqs are rank-skewed "
                          "by the background client; the online "
                          "fold-index hash remains authoritative")
    return report


# ---------------------------------------------------------------------------
# the full analysis (CLI entry)
# ---------------------------------------------------------------------------

def analyze(paths, merged_out=None):
    """Load every artifact, align clocks, merge, and analyze.  Returns
    ``(report, merged_trace_dict)``; the report's ``problems`` list is
    empty on a fully valid result (the CLI's exit code)."""
    artifacts = [load_artifact(p) for p in paths]
    problems = _assign_ranks(artifacts)
    offsets = clock_offsets(artifacts)
    trace, links = merged_trace(artifacts, offsets)
    problems += _tracing.validate_chrome_trace(trace)
    rows, summary = straggler_table(artifacts, offsets)
    ranks_info = {}
    for a in artifacts:
        info = ranks_info.setdefault(str(a["rank"]), {
            "sources": [], "collectives": 0, "heartbeats": 0})
        info["sources"].append("%s (%s)" % (a["source"], a["kind"]))
        info["collectives"] += len(a["collectives"])
        info["heartbeats"] += len(a["heartbeats"])
    report = {
        "ranks": ranks_info,
        "clock_offsets_s": {str(r): round(offsets[r], 6) for r in offsets},
        "merged_events": len(trace["traceEvents"]),
        "cross_rank_flow_links": links,
        "straggler_summary": summary,
        "stragglers": rows,
        "lockstep": lockstep_check(artifacts),
        "problems": problems,
    }
    if merged_out:
        with open(merged_out, "w") as f:
            json.dump(trace, f)
        report["merged_path"] = merged_out
    return report, trace


# ---------------------------------------------------------------------------
# graftpulse: profiler-trace ingestion (the async-ledger fallback)
# ---------------------------------------------------------------------------

# the trace-parsing core (interval union, device-event detection, the
# per-step row convention) is SHARED with the online graftxray capture
# path — one parser, online + offline (telemetry/xray.py); the private
# names stay as aliases for the existing callers and tests
from . import xray as _xray

_merge_intervals = _xray.merge_intervals
_DEVICE_PID_HINTS = _xray.DEVICE_PID_HINTS


def _is_device_event(ev, device_pids):
    """Shared-core device-span detection (see xray.is_device_event)."""
    return _xray.is_device_event(ev, device_pids)


def ingest_xla(path_or_doc):
    """Rebuild the per-step device ledger OFFLINE from a chrome trace —
    the fallback for runs where the pulse done-callbacks were
    unavailable (``GRAFT_PULSE=0``, external XLA profiler captures).

    Device-busy spans are unioned per step (``args.step`` stamps, the
    same id graftlens threads onto flush spans; unstamped device spans
    pool into one unattributed window).  Step windows follow the live
    lens convention — previous step's window end to this step's — so
    ``busy_s + idle_s == wall_s`` holds exactly per row, same contract
    as the online ledger.  The grouping, the union and the row
    convention are the graftxray shared core (``xray.step_spans`` /
    ``xray.step_rows``) — the online capture parser and this offline
    CLI cannot drift apart.  Returns the report dict (``steps`` rows +
    ``total``); CLI: ``telemetry --ingest-xla PATH [--json]``."""
    events = _xray.load_trace(path_or_doc)
    by_step, n_device, _dpids = _xray.step_spans(events)
    rows, nonmono, total = _xray.step_rows(by_step)
    report = {
        "device_events": n_device,
        "steps": rows,
        "total": total,
        "problems": [] if n_device else [
            "no device-busy spans found (no args.device_time spans, no "
            "device-named process track, no device cat) — was the trace "
            "captured with profiler sync mode or an XLA profiler?"],
    }
    if nonmono:
        report["problems"].append(
            "step ids are not time-monotonic (steps %s have every span "
            "before the previous step's window end — a restarted step "
            "counter or merged captures?): their wall_s/busy_s clamped "
            "to 0 and real device time is missing from those rows"
            % sorted(nonmono, key=str))
    return report


# ---------------------------------------------------------------------------
# selftest (lint smoke tier)
# ---------------------------------------------------------------------------

def _synthetic_dump(rank, delay_s, base=1700000000.0, steps=3,
                    buckets=("bucket[float32:4p:4096B]",
                             "bucket[float32:3p:3072B]")):
    """A minimal but schema-faithful flight-recorder dump: per step, one
    reduce collective per bucket (the delayed rank enters ``delay_s``
    late; every rank exits together, as a sync allreduce does) plus one
    piggybacked heartbeat."""
    events = []
    seq = 0
    for step in range(1, steps + 1):
        t_step = base + step * 0.5
        for b, label in enumerate(buckets):
            seq += 1
            slot = t_step + b * 0.05
            enter = slot + (delay_s if rank == 1 else 0.0)
            exit_ = slot + delay_s + 0.005
            events.append({"ts": exit_, "kind": "collective", "data": {
                "path": "reduce_many", "seq": seq, "step": step,
                "bucket": label, "n_keys": 1, "nbytes": 4096,
                "rank": rank,
                "latency_ms": round((exit_ - enter) * 1e3, 3)}})
        hb_t = t_step + 0.2
        events.append({"ts": hb_t, "kind": "dist_heartbeat",
                       "data": {"workers": 2, "step": step,
                                "skew_s": delay_s}})
        events.append({"ts": hb_t + 0.01, "kind": "engine_flush",
                       "data": {"segment": step, "cause": "autograd",
                                "nodes": 8, "live_outputs": 1,
                                "cache": "hit", "latency_ms": 2.0,
                                "step": step}})
        events.append({"ts": hb_t + 0.02, "kind": "step",
                       "data": {"origin": "trainer", "index": step,
                                "step": step, "latency_ms": 40.0,
                                "phases": {"kvstore": 0.02,
                                           "update": 0.01}}})
    return {
        "schema": _BLACKBOX_SCHEMA, "pid": 1000 + rank, "rank": rank,
        "clock_offset_s": 0.0, "reason": "manual",
        "dumped_at": base + 100.0, "started_at": base,
        "ring_size": 4096, "events_total": len(events),
        "last_progress": {"ts": base + 100.0, "site": "selftest",
                          "age": 0.0},
        "in_flight": [], "failures": [], "workers": {},
        "events": events, "threads": {},
    }


def selftest():
    """Exercise the whole aggregation pipeline on two synthetic rank
    dumps with rank 1 deliberately delayed.  Returns a list of problems
    — empty means pass (wired into tools/run_lint.sh)."""
    delay = 0.15
    buckets = ("bucket[float32:4p:4096B]", "bucket[float32:3p:3072B]")
    paths = []
    problems = []
    try:
        for rank in (0, 1):
            fd, p = tempfile.mkstemp(suffix=".json",
                                     prefix="graftlens_self_r%d_" % rank)
            with os.fdopen(fd, "w") as f:
                json.dump(_synthetic_dump(rank, delay, buckets=buckets), f)
            paths.append(p)
        fd, merged_path = tempfile.mkstemp(suffix=".json",
                                           prefix="graftlens_self_merged_")
        os.close(fd)
        paths.append(merged_path)
        report, trace = analyze(paths[:2], merged_out=merged_path)
        problems += list(report["problems"])
        # per-rank tracks present
        names = {(e.get("pid"), (e.get("args") or {}).get("name"))
                 for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        for r in (0, 1):
            if not any(pid == r for pid, _n in names):
                problems.append("merged trace lost rank %d's track" % r)
        # >= 1 cross-rank flow link per reduced bucket
        rows = report["stragglers"]
        for label in buckets:
            if not any(r["label"] == label for r in rows):
                problems.append("no straggler row for %s" % label)
        if report["cross_rank_flow_links"] < len(buckets):
            problems.append("expected >= %d cross-rank flow links, got %d"
                            % (len(buckets),
                               report["cross_rank_flow_links"]))
        # the table must blame the delayed rank
        summary = report["straggler_summary"]
        if summary["worst_rank"] != 1:
            problems.append("straggler table blamed rank %r, expected the "
                            "delayed rank 1" % (summary["worst_rank"],))
        if not (0.9 * delay < summary["max_enter_spread_s"]
                < 1.1 * delay + 0.01):
            problems.append("enter spread %.3fs does not reflect the "
                            "%.3fs delay" % (summary["max_enter_spread_s"],
                                             delay))
        if summary["collectives_matched"] == 0:
            problems.append("straggler table empty")
        # the merged file written by --merged must itself validate
        with open(merged_path) as f:
            problems += _tracing.validate_chrome_trace(json.load(f))
        return problems
    finally:
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
