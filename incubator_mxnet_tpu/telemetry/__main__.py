"""graftscope + graftwatch + graftlens CLI.

    python -m incubator_mxnet_tpu.telemetry --summary [--json]
        Run one bulked training step (gluon Trainer on CPU, a kvstore
        attached) with segment tracing on, then render the top-k segment
        flushes by device time and the metrics snapshot (flush causes,
        kvstore bytes, device-memory gauges) FROM THAT RUN.

    python -m incubator_mxnet_tpu.telemetry --steps [--json]
        graftlens live-ring demo: run a short gluon training loop (io
        iterator -> record/backward -> Trainer.step on a kvstore) and
        render the per-step wall-time attribution ring — each step's
        data_wait/forward/backward/exposed_comm/update/host_gap
        breakdown plus the mean fractions.

    python -m incubator_mxnet_tpu.telemetry --analyze R0.json R1.json...
        [--json | --merged OUT.json]
        Cross-rank analysis: merge N per-rank chrome traces and/or
        flight-recorder dumps into one clock-aligned trace (per-rank
        process tracks, cross-rank flow links per collective) and print
        the straggler table (last-to-enter/exit rank, enter/exit
        spreads, per-rank blame counts).  --merged writes the merged
        chrome trace; exits 1 on schema problems.

    python -m incubator_mxnet_tpu.telemetry --analyze --selftest
        Lint smoke tier for the aggregator: two synthetic rank dumps
        (rank 1 deliberately delayed) must merge into a schema-valid
        trace whose straggler table blames rank 1.

    python -m incubator_mxnet_tpu.telemetry --summary --trace T.json
        Same report over an existing chrome-trace dump (segment table
        from the file; the metrics section reflects this process).

    python -m incubator_mxnet_tpu.telemetry --blackbox PATH [--json]
        Post-mortem: reconstruct the final timeline from a flight-
        recorder dump — reason, what was in flight (stuck segment /
        collective / phase), the last engine flushes, step journal with
        phase latencies, per-worker last-seen, watchdog verdict.
        Exits 1 when the dump fails schema validation.

    python -m incubator_mxnet_tpu.telemetry --selftest
        Lint smoke tier: bulk a 3-op program, dump a trace, validate the
        chrome-trace schema + non-empty flow links.  Exit 1 on any
        regression.

    python -m incubator_mxnet_tpu.telemetry --blackbox --selftest
        Lint smoke tier for the flight recorder: exercise the full
        pipeline (flushes, collectives, a step journal, an in-flight
        bracket) and validate the dump schema.

``GRAFT_TELEMETRY_TOPK`` (default 10) sizes the segment table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# pin jax to CPU before anything initializes a backend: the CLI must
# work (and stay fast) on machines whose TPU is busy or absent
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _demo_training_step():
    """One bulked gluon training step with every telemetry surface lit:
    engine segments, autograd, kvstore push/pull, io batches."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, gluon, io, profiler

    net = gluon.nn.Dense(8)
    net.initialize()
    kvs = mx.kv.create("local")
    x = mx.nd.array(np.random.RandomState(0).rand(4, 16).astype(np.float32))
    y = mx.nd.array(np.zeros((4, 8), np.float32))
    it = io.NDArrayIter(data=x.asnumpy(), label=y.asnumpy(), batch_size=4)
    net(x).asnumpy()                       # param init outside the trace
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kvs)

    fd, path = tempfile.mkstemp(suffix=".json", prefix="graftscope_")
    os.close(fd)
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")
    for batch in it:
        data = batch.data[0]
        with engine.bulk(64):
            with autograd.record():
                out = net(data)
                loss = (out * out).mean()
            loss.backward()
        trainer.step(batch_size=data.shape[0])
        loss.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    os.unlink(path)
    return trace


def _summary(trace_events, top):
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.telemetry import tracing
    report = tracing.segment_summary(trace_events, top=top)
    snap = telemetry.registry().snapshot()
    report["metrics"] = snap
    report["flush_causes"] = {
        s["labels"]["cause"]: s["value"]
        for s in snap.get("graft_engine_flushes_total",
                          {"samples": []})["samples"]}
    report["kvstore_bytes"] = {
        k.replace("graft_kvstore_", "").replace("_total", ""): v
        for k, v in telemetry.compact_snapshot().items()
        if k.startswith("graft_kvstore_")}
    report["device_memory"] = [
        dict(s["labels"], bytes=s["value"])
        for s in snap.get("graft_device_memory_bytes",
                          {"samples": []})["samples"]]
    return report


def _render_text(report):
    lines = ["graftscope summary", "=" * 60]
    lines.append("top segments by flush time (%d total):"
                 % report["segments_total"])
    lines.append("%-8s %-12s %6s %12s %6s %s"
                 % ("segment", "cause", "nodes", "dur(us)", "cache",
                    "device_time"))
    for s in report["top_segments"]:
        lines.append("%-8s %-12s %6s %12.1f %6s %s"
                     % (s["segment"], s["cause"], s["nodes"],
                        s["duration_us"], s["cache"], s["device_time"]))
    lines.append("")
    lines.append("flush time by cause (us): %s"
                 % json.dumps(report["flush_causes_us"]))
    lines.append("flush counts by cause:    %s"
                 % json.dumps(report["flush_causes"]))
    lines.append("kvstore bytes:            %s"
                 % json.dumps(report["kvstore_bytes"]))
    lines.append("")
    lines.append("device memory:")
    for m in report["device_memory"]:
        lines.append("  %-24s %-8s %16d" % (m["device"], m["kind"],
                                            int(m["bytes"])))
    lines.append("")
    lines.append("full metrics snapshot: %d metric families"
                 % len(report["metrics"]))
    for k, v in sorted(report["metrics"].items()):
        lines.append("  %-40s %s (%d series)"
                     % (k, v["kind"], len(v["samples"])))
    return "\n".join(lines)


def selftest():
    """Trace a 3-op bulked program and validate the dump (lint tier).
    Returns a list of problems — empty means pass."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine, profiler
    from incubator_mxnet_tpu.telemetry import tracing

    fd, path = tempfile.mkstemp(suffix=".json", prefix="graftscope_self_")
    os.close(fd)
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")
    a = mx.nd.array(np.ones((8, 8), np.float32))
    with engine.bulk(16):
        b = a * a
        c = b + a
        d = c - a
        d.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    os.unlink(path)
    problems = tracing.validate_chrome_trace(trace)
    events = trace["traceEvents"]
    flows = [e for e in events if e.get("ph") in ("s", "f")]
    if not flows:
        problems.append("no flow events in the trace (record→flush links "
                        "are gone)")
    deferred = [e for e in events
                if e.get("args", {}).get("deferred") is True]
    if len(deferred) < 3:
        problems.append("expected >=3 deferred op records, got %d"
                        % len(deferred))
    segs = [e for e in events if e.get("name") == tracing.SEGMENT_SPAN]
    if not segs:
        problems.append("no bulk_segment_flush span")
    elif segs[0].get("args", {}).get("nodes") != 3:
        problems.append("segment span nodes=%r, expected 3"
                        % segs[0].get("args", {}).get("nodes"))
    return problems


def _render_blackbox_text(report):
    """Human rendering of summarize_dump(): the final-timeline view."""
    import datetime

    def when(ts):
        try:
            return datetime.datetime.fromtimestamp(ts).isoformat(
                timespec="milliseconds")
        except (OverflowError, OSError, ValueError, TypeError):
            return str(ts)

    lines = ["graftwatch post-mortem", "=" * 60]
    lines.append("reason: %-12s pid: %-8s rank: %s"
                 % (report["reason"], report["pid"], report["rank"]))
    lines.append("dumped at: %s" % when(report["dumped_at"]))
    lp = report.get("last_progress") or {}
    lines.append("last progress: %.3fs before dump (%s)"
                 % (lp.get("age", 0.0), lp.get("site", "?")))
    lines.append("events: %s held of %s recorded  %s"
                 % (report["events_held"], report["events_total"],
                    json.dumps(report["counts"])))
    if report.get("watchdog"):
        wd = report["watchdog"]
        lines.append("")
        lines.append("WATCHDOG TRIP: %r stuck %.1fs (timeout %.1fs) "
                     "detail=%s" % (wd.get("tripped_site"),
                                    wd.get("age_s", 0.0),
                                    wd.get("timeout_s", 0.0),
                                    json.dumps(wd.get("tripped_detail"))))
    if report.get("exception"):
        ex = report["exception"]
        lines.append("")
        lines.append("EXCEPTION: %s: %s" % (ex.get("type"), ex.get("value")))
    if report["in_flight"]:
        lines.append("")
        lines.append("in flight at dump time:")
        for e in report["in_flight"]:
            lines.append("  %-12s age %8.3fs  thread %-12s %s"
                         % (e.get("site"), e.get("age", 0.0),
                            e.get("thread", "?"),
                            json.dumps(e.get("detail"))))
    if report["failures"]:
        lines.append("")
        lines.append("recent bracket failures:")
        for e in report["failures"]:
            lines.append("  %-12s after %7.3fs  %s — %s"
                         % (e.get("site"), e.get("seconds", 0.0),
                            json.dumps(e.get("detail")), e.get("error")))
    lines.append("")
    lines.append("last engine flushes (newest last):")
    lines.append("  %9s %-12s %6s %6s %10s %6s"
                 % ("age(s)", "cause", "nodes", "live", "lat(ms)", "cache"))
    for e in report["last_flushes"]:
        lines.append("  %9.3f %-12s %6s %6s %10.3f %6s%s"
                     % (e.get("age_s", 0.0), e.get("cause"),
                        e.get("nodes"), e.get("live_outputs"),
                        e.get("latency_ms", 0.0), e.get("cache"),
                        "  ERROR: %s" % e["error"] if "error" in e else ""))
    if report["last_steps"]:
        lines.append("")
        lines.append("last steps:")
        for e in report["last_steps"]:
            lines.append("  %9.3fs ago  %-8s #%-6s %8.3fms  phases %s%s%s"
                         % (e.get("age_s", 0.0), e.get("origin"),
                            e.get("index"), e.get("latency_ms", 0.0),
                            json.dumps(e.get("phases")),
                            "  mem_peak %d" % e["device_mem_peak"]
                            if "device_mem_peak" in e else "",
                            "  ERROR %s" % (e.get("error_phase")
                                            or e.get("error"))
                            if ("error" in e or "error_phase" in e) else ""))
    comp = report.get("compiled") or {}
    if comp.get("steps_total") or comp.get("last_transitions") \
            or comp.get("auditor_reports"):
        lines.append("")
        lines.append("compiled path (graftstep/graftguard):")
        lines.append("  %s of %s journaled steps ran compiled"
                     % (comp.get("steps_compiled", 0),
                        comp.get("steps_total", 0)))
        for e in comp.get("last_transitions") or []:
            # the diffed guard-key component is the interesting name;
            # the structural reason only matters when there is no diff
            lines.append("  %9.3fs ago  %-10s %s%s"
                         % (e.get("age_s", 0.0), e.get("event"),
                            e.get("component") or e.get("reason") or "",
                            "  (%s)" % e["detail"]
                            if e.get("detail") else ""))
        for e in comp.get("auditor_reports") or []:
            lines.append("  %9.3fs ago  %-10s %s"
                         % (e.get("age_s", 0.0), e.get("code"),
                            (e.get("msg") or "")[:120]))
    if report["last_collectives"]:
        lines.append("")
        lines.append("last collectives:")
        for e in report["last_collectives"]:
            lines.append("  %9.3fs ago  %-12s keys %-5s bytes %-10s "
                         "%8.3fms rank %s"
                         % (e.get("age_s", 0.0), e.get("path"),
                            e.get("n_keys"), e.get("nbytes", "?"),
                            e.get("latency_ms", 0.0), e.get("rank")))
    if report["slow_collectives"]:
        lines.append("")
        lines.append("slow collectives (beyond EWMA x factor):")
        for e in report["slow_collectives"]:
            lines.append("  %9.3fs ago  %-12s %8.3fms (ewma %.3fms)"
                         % (e.get("age_s", 0.0), e.get("path"),
                            e.get("latency_ms", 0.0), e.get("ewma_ms", 0.0)))
    if report["workers"]:
        lines.append("")
        lines.append("per-worker last seen (dist heartbeat):")
        for r in sorted(report["workers"], key=str):
            w = report["workers"][r]
            lines.append("  rank %-4s step %-8s lag %8.3fs  info age %.3fs"
                         % (r, w.get("step"), w.get("lag_s", 0.0),
                            w.get("info_age_s", 0.0)))
    return "\n".join(lines)


def _demo_lens_steps(n_steps=6):
    """A short real training loop with every lens source lit: io
    iterator (data_wait), record scope (forward), backward, a local
    kvstore (exposed_comm) and the fused update — fills the lens ring."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, gluon, io
    from incubator_mxnet_tpu.telemetry import lens

    prev = lens._enabled_override
    lens.set_enabled(True)      # the demo must work under GRAFT_LENS=0
    try:
        lens.reset()
        net = gluon.nn.Dense(8)
        net.initialize()
        rs = np.random.RandomState(0)
        x = rs.rand(4 * n_steps, 16).astype(np.float32)
        y = np.zeros((4 * n_steps, 8), np.float32)
        net(mx.nd.array(x[:4])).asnumpy()      # param init outside
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1},
                                kvstore=mx.kv.create("local"))
        it = io.NDArrayIter(data=x, label=y, batch_size=4)
        for batch in it:
            data = batch.data[0]
            with engine.bulk(64):       # flush boundaries light the
                #                         pulse + memory-timeline sites
                with autograd.record():
                    out = net(data)
                    loss = (out * out).mean()
                loss.backward()
            trainer.step(batch_size=data.shape[0])
            loss.asnumpy()
        lens.pulse_drain(2.0)           # settle async ledger bookings
        return lens.steps()
    finally:
        lens.set_enabled(prev)


def _render_lens_text(records, agg):
    from incubator_mxnet_tpu.telemetry.lens import ABBREV, COMPONENTS
    short = dict(ABBREV)
    lines = ["graftlens step attribution (%d steps in ring)"
             % len(records), "=" * 72]
    lines.append("%-5s %-8s %9s  %s" % (
        "step", "origin", "wall(ms)",
        " ".join("%7s" % short[c] for c in COMPONENTS)))
    for r in records:
        lines.append("%-5d %-8s %9.2f  %s" % (
            r["step"], r["origin"], r["wall_s"] * 1e3,
            " ".join("%7.2f" % (r["components"][c] * 1e3)
                     for c in COMPONENTS)))
    if agg.get("steps"):
        fr = agg["fractions"]
        lines.append("")
        lines.append("mean %.2fms/step | %s" % (
            agg["mean_step_ms"],
            " ".join("%s %d%%" % (short[c], round(fr[c] * 100))
                     for c in COMPONENTS)))
        lines.append("comm blocked %.2fms / in-flight %.2fms over the ring"
                     % (agg["comm_blocked_s"] * 1e3,
                        agg["comm_inflight_s"] * 1e3))
    return "\n".join(lines)


def run_steps(as_json):
    from incubator_mxnet_tpu.telemetry import lens
    records = _demo_lens_steps()
    agg = lens.summary(records)
    if as_json:
        print(json.dumps({"steps": records, "summary": agg}, indent=2,
                         sort_keys=True, default=str))
    else:
        print(_render_lens_text(records, agg))
    return 0 if records else 1


def _render_analyze_text(report):
    lines = ["graftlens cross-rank analysis", "=" * 72]
    for r in sorted(report["ranks"], key=int):
        info = report["ranks"][r]
        lines.append("rank %-3s %-40s collectives %-5d heartbeats %d"
                     % (r, ", ".join(info["sources"]),
                        info["collectives"], info["heartbeats"]))
    lines.append("clock offsets vs first rank (s): %s"
                 % json.dumps(report["clock_offsets_s"]))
    lines.append("merged trace: %d events, %d cross-rank flow links%s"
                 % (report["merged_events"],
                    report["cross_rank_flow_links"],
                    ", written to %s" % report["merged_path"]
                    if "merged_path" in report else ""))
    rows = sorted(report["stragglers"],
                  key=lambda r: -r["enter_spread_s"])[:10]
    if rows:
        lines.append("")
        lines.append("straggler table (top %d by enter spread):" % len(rows))
        lines.append("%-6s %-28s %-6s %-10s %-9s %14s %14s"
                     % ("step", "collective", "ranks", "last-enter",
                        "last-exit", "enter-sprd(ms)", "exit-sprd(ms)"))
        for r in rows:
            # async reduces carry no wire-synchronized exit (host-local
            # wait-return): their exit columns render as "-"
            exit_rank = "-" if r["last_to_exit"] is None \
                else r["last_to_exit"]
            exit_sprd = "%14s" % "-" if r["exit_spread_s"] is None \
                else "%14.3f" % (r["exit_spread_s"] * 1e3)
            lines.append("%-6s %-28s %-6d %-10s %-9s %14.3f %s"
                         % (r["step"], r["label"][:28], len(r["ranks"]),
                            r["last_to_enter"], exit_rank,
                            r["enter_spread_s"] * 1e3, exit_sprd))
        s = report["straggler_summary"]
        lines.append("")
        lines.append("blame (times last-to-enter): %s"
                     % json.dumps(s["blame"]))
        lines.append("worst rank: %s   max enter spread: %.3fms   "
                     "mean: %.3fms"
                     % (s["worst_rank"], s["max_enter_spread_s"] * 1e3,
                        s["mean_enter_spread_s"] * 1e3))
    else:
        lines.append("no cross-rank collectives matched (single artifact "
                     "or disjoint sequences)")
    ls = report.get("lockstep") or {}
    lines.append("")
    if ls.get("first_divergent_seq") is not None:
        lines.append("LOCKSTEP DIVERGENCE: rank(s) %s diverged — first "
                     "bad seq %s (%d mismatch(es), %d hole(s) over %d "
                     "matched seq(s))"
                     % (ls.get("divergent_ranks"),
                        ls["first_divergent_seq"],
                        len(ls.get("mismatches") or ()),
                        len(ls.get("holes") or ()),
                        ls.get("seqs_checked", 0)))
        for m in (ls.get("mismatches") or ())[:3]:
            lines.append("  seq %-6s per-rank (path, n_keys, nbytes, "
                         "label): %s" % (m["seq"],
                                         json.dumps(m["per_rank"])))
        for h in (ls.get("holes") or ())[:3]:
            lines.append("  seq %-6s missing on rank %s"
                         % (h["seq"], h["missing_rank"]))
    elif ls.get("seqs_checked"):
        lines.append("lockstep: %d matched collective seq(s), streams "
                     "identical on ranks %s"
                     % (ls["seqs_checked"], ls.get("ranks")))
    elif ls.get("note"):
        lines.append("lockstep: audit declined — %s" % ls["note"])
    for r in ls.get("online_reports") or ():
        lines.append("  online divergence report (rank %s): first bad "
                     "stream position <= %s, hashes %s"
                     % (r.get("rank"),
                        r.get("first_divergent_fold",
                              r.get("first_divergent_seq")),
                        json.dumps(r.get("rank_hashes"))))
    for p in report["problems"]:
        lines.append("PROBLEM: %s" % p)
    return "\n".join(lines)


def _render_ingest_text(report):
    lines = ["graftpulse device-ledger ingestion", "=" * 60]
    lines.append("device-busy spans: %d" % report["device_events"])
    lines.append("%-8s %10s %10s %10s %7s %6s"
                 % ("step", "wall(ms)", "busy(ms)", "idle(ms)", "busy%",
                    "spans"))
    for r in report["steps"]:
        lines.append("%-8s %10.3f %10.3f %10.3f %6.1f%% %6d"
                     % (r["step"] if r["step"] is not None else "-",
                        r["wall_s"] * 1e3, r["busy_s"] * 1e3,
                        r["idle_s"] * 1e3, r["busy_fraction"] * 100,
                        r["spans"]))
    t = report["total"]
    lines.append("total    %10.3f %10.3f %10.3f %6.1f%%"
                 % (t["wall_s"] * 1e3, t["busy_s"] * 1e3,
                    t["idle_s"] * 1e3, t["busy_fraction"] * 100))
    for p in report["problems"]:
        lines.append("PROBLEM: %s" % p)
    return "\n".join(lines)


def run_ingest(path, as_json):
    from incubator_mxnet_tpu.telemetry import aggregate
    report = aggregate.ingest_xla(path)
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(_render_ingest_text(report))
    return 1 if report["problems"] else 0


def _render_xray_text(sessions):
    lines = ["graftxray capture sessions", "=" * 60]
    if not sessions:
        lines.append("(no capture sessions — arm with GRAFT_XRAY=1 and "
                     "trigger via GRAFT_XRAY_EVERY, a slow step, a "
                     "watchdog trip, or xray.request_capture())")
    for s in sessions:
        lines.append("session: reason=%s steps=%s ok=%s"
                     % (s.get("reason"), s.get("steps"), s.get("ok")))
        if s.get("error"):
            lines.append("  ERROR: %s" % s["error"])
        rep = s.get("report") or {}
        phases = rep.get("phases") or s.get("phases") or {}
        for p in sorted(phases):
            d = phases[p]
            dev = d["device_s"] if isinstance(d, dict) else d
            lines.append("  %-22s %10.3f ms" % (p, dev * 1e3))
        un = rep.get("unattributed_s", s.get("unattributed_s"))
        tot = rep.get("program_device_s", s.get("program_device_s"))
        if un is not None:
            lines.append("  %-22s %10.3f ms" % ("unattributed", un * 1e3))
        if tot is not None:
            cons = rep.get("conservation_ok", s.get("conservation_ok"))
            lines.append("  %-22s %10.3f ms  (conservation %s)"
                         % ("program span", tot * 1e3,
                            "EXACT" if cons else "VIOLATED"))
        for r in (rep.get("top_ops") or s.get("top_ops") or [])[:8]:
            dev_us = r.get("device_us", r.get("device_s", 0.0) * 1e6)
            lines.append("    op %-32s phase=%-14s %9.1f us x%s"
                         % (r["op"][:32], r.get("phase") or "-",
                            dev_us, r.get("count", "?")))
    return "\n".join(lines)


def run_xray(path, as_json):
    """``--xray``: render capture sessions — live harness state when no
    path is given, else the ``xray_capture`` events of a blackbox dump."""
    from incubator_mxnet_tpu.telemetry import xray
    if path:
        with open(path) as f:
            doc = json.load(f)
        # dump events nest the fields under "data" ({"ts", "kind",
        # "data": {...}} — blackbox.events()); flatten for the renderer
        sessions = [dict(e.get("data") or {},
                         ok=(e.get("data") or {}).get("ok", True))
                    for e in doc.get("events", [])
                    if e.get("kind") == "xray_capture"]
    else:
        sessions = xray.sessions()
    if as_json:
        print(json.dumps(sessions, indent=2, sort_keys=True, default=str))
    else:
        print(_render_xray_text(sessions))
    return 0


def _demo_mem_steps():
    """The --steps demo loop with the exact live-arrays memory sampler
    installed (host CPU reports no allocator counters, so the default
    per-flush sampler would auto-disable)."""
    from incubator_mxnet_tpu.telemetry import lens
    lens.set_mem_sampler(lens.live_arrays_sampler)
    try:
        records = _demo_lens_steps()
    finally:
        lens.set_mem_sampler(None)
    return records, lens.mem_summary()


def _render_mem_text(records, sites):
    lines = ["graftpulse memory timeline (per-site allocation watermarks)",
             "=" * 72]
    lines.append("%-32s %8s %14s %14s"
                 % ("site", "samples", "peak(bytes)", "last-in-use"))
    for site in sorted(sites, key=lambda s: -sites[s]["peak_bytes"]):
        s = sites[site]
        lines.append("%-32s %8d %14d %14d"
                     % (site[:32], s["samples"], s["peak_bytes"],
                        s["last_in_use"]))
    lines.append("")
    lines.append("per-step window peaks:")
    lines.append("%-5s %-8s %9s %14s %6s" % ("step", "origin", "wall(ms)",
                                             "mem-peak(bytes)", "sites"))
    for r in records:
        mem = r.get("mem") or {}
        lines.append("%-5d %-8s %9.2f %14s %6d"
                     % (r["step"], r["origin"], r["wall_s"] * 1e3,
                        mem.get("peak_bytes", "-"),
                        len(mem.get("sites", ()))))
    return "\n".join(lines)


def run_mem(as_json):
    records, sites = _demo_mem_steps()
    if as_json:
        print(json.dumps({"sites": sites,
                          "steps": [{"step": r["step"],
                                     "mem": r.get("mem")}
                                    for r in records]},
                         indent=2, sort_keys=True, default=str))
    else:
        print(_render_mem_text(records, sites))
    return 0 if sites else 1


def run_analyze(paths, merged_out, as_json):
    from incubator_mxnet_tpu.telemetry import aggregate
    report, _trace = aggregate.analyze(paths, merged_out=merged_out)
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(_render_analyze_text(report))
    return 1 if report["problems"] else 0


def analyze_selftest():
    from incubator_mxnet_tpu.telemetry import aggregate
    problems = aggregate.selftest()
    if problems:
        for p in problems:
            print("graftlens analyze selftest FAIL: %s" % p,
                  file=sys.stderr)
        return 1
    print("graftlens analyze selftest OK (merged trace valid, straggler "
          "table blames the delayed rank)")
    return 0


def blackbox_selftest():
    """Flight-recorder lint smoke: full-pipeline dump + schema check."""
    from incubator_mxnet_tpu.telemetry import blackbox
    problems = blackbox.selftest()
    if problems:
        for p in problems:
            print("graftwatch selftest FAIL: %s" % p, file=sys.stderr)
        return 1
    print("graftwatch selftest OK (ring + brackets + dump schema valid)")
    return 0


def render_blackbox(path, as_json):
    from incubator_mxnet_tpu.telemetry import blackbox
    with open(path) as f:
        doc = json.load(f)
    problems = blackbox.validate_dump(doc)
    report = blackbox.summarize_dump(doc)
    if as_json:
        out = dict(report, problems=problems)
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
    else:
        print(_render_blackbox_text(report))
        for p in problems:
            print("graftwatch: dump schema problem: %s" % p,
                  file=sys.stderr)
    return 1 if problems else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.telemetry",
        description="graftscope: segment-aware tracing + metrics summary; "
                    "graftwatch: flight-recorder post-mortems; graftlens: "
                    "per-step attribution + cross-rank straggler analysis")
    ap.add_argument("--summary", action="store_true",
                    help="run (or load) a traced workload and report")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--trace", metavar="PATH",
                    help="summarize an existing chrome-trace dump instead "
                         "of running the demo step")
    ap.add_argument("--blackbox", metavar="PATH", nargs="?", const="",
                    default=None,
                    help="render a flight-recorder dump (with --selftest: "
                         "validate the recorder pipeline instead)")
    ap.add_argument("--analyze", metavar="ARTIFACT", nargs="*",
                    default=None,
                    help="merge + analyze N per-rank chrome traces and/or "
                         "blackbox dumps (with --selftest: synthetic "
                         "2-rank smoke)")
    ap.add_argument("--merged", metavar="OUT",
                    help="with --analyze: write the merged chrome trace "
                         "here")
    ap.add_argument("--steps", action="store_true",
                    help="run a short training loop and render the "
                         "graftlens per-step attribution ring")
    ap.add_argument("--mem", action="store_true",
                    help="run the demo loop with the exact memory "
                         "sampler and render the graftpulse per-site "
                         "allocation-watermark timeline")
    ap.add_argument("--ingest-xla", metavar="TRACE", dest="ingest_xla",
                    help="rebuild the per-step device ledger offline "
                         "from a chrome trace (the async-ledger "
                         "fallback when pulse callbacks were "
                         "unavailable)")
    ap.add_argument("--xray", metavar="DUMP", nargs="?", const="",
                    default=None,
                    help="render graftxray capture sessions (phase "
                         "device-time tables of the compiled step) — "
                         "live harness state, or the xray_capture "
                         "events of a blackbox dump PATH")
    ap.add_argument("--top", type=int,
                    default=int(os.environ.get("GRAFT_TELEMETRY_TOPK",
                                               "10")),
                    help="segment table size (GRAFT_TELEMETRY_TOPK)")
    ap.add_argument("--selftest", action="store_true",
                    help="trace a 3-op bulked program and validate the "
                         "dump (CI smoke tier)")
    args = ap.parse_args(argv)

    if args.analyze is not None:
        if args.selftest:
            return analyze_selftest()
        if not args.analyze:
            ap.error("--analyze needs artifact PATHs (or --selftest)")
        return run_analyze(args.analyze, args.merged, args.json)

    if args.ingest_xla:
        return run_ingest(args.ingest_xla, args.json)

    if args.xray is not None:
        return run_xray(args.xray, args.json)

    if args.steps:
        return run_steps(args.json)

    if args.mem:
        return run_mem(args.json)

    if args.blackbox is not None:
        if args.selftest:
            return blackbox_selftest()
        if not args.blackbox:
            ap.error("--blackbox needs a dump PATH (or --selftest)")
        return render_blackbox(args.blackbox, args.json)

    if args.selftest:
        problems = selftest()
        if problems:
            for p in problems:
                print("graftscope selftest FAIL: %s" % p, file=sys.stderr)
            return 1
        print("graftscope selftest OK (schema + flow links valid)")
        return 0

    if not args.summary:
        ap.print_help()
        return 2

    if args.trace:
        with open(args.trace) as f:
            events = json.load(f)["traceEvents"]
    else:
        events = _demo_training_step()["traceEvents"]
    report = _summary(events, args.top)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(_render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
