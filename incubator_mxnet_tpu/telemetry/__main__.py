"""graftscope CLI.

    python -m incubator_mxnet_tpu.telemetry --summary [--json]
        Run one bulked training step (gluon Trainer on CPU, a kvstore
        attached) with segment tracing on, then render the top-k segment
        flushes by device time and the metrics snapshot (flush causes,
        kvstore bytes, device-memory gauges) FROM THAT RUN.

    python -m incubator_mxnet_tpu.telemetry --summary --trace T.json
        Same report over an existing chrome-trace dump (segment table
        from the file; the metrics section reflects this process).

    python -m incubator_mxnet_tpu.telemetry --selftest
        Lint smoke tier: bulk a 3-op program, dump a trace, validate the
        chrome-trace schema + non-empty flow links.  Exit 1 on any
        regression.

``GRAFT_TELEMETRY_TOPK`` (default 10) sizes the segment table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# pin jax to CPU before anything initializes a backend: the CLI must
# work (and stay fast) on machines whose TPU is busy or absent
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _demo_training_step():
    """One bulked gluon training step with every telemetry surface lit:
    engine segments, autograd, kvstore push/pull, io batches."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, gluon, io, profiler

    net = gluon.nn.Dense(8)
    net.initialize()
    kvs = mx.kv.create("local")
    x = mx.nd.array(np.random.RandomState(0).rand(4, 16).astype(np.float32))
    y = mx.nd.array(np.zeros((4, 8), np.float32))
    it = io.NDArrayIter(data=x.asnumpy(), label=y.asnumpy(), batch_size=4)
    net(x).asnumpy()                       # param init outside the trace
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kvs)

    fd, path = tempfile.mkstemp(suffix=".json", prefix="graftscope_")
    os.close(fd)
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")
    for batch in it:
        data = batch.data[0]
        with engine.bulk(64):
            with autograd.record():
                out = net(data)
                loss = (out * out).mean()
            loss.backward()
        trainer.step(batch_size=data.shape[0])
        loss.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    os.unlink(path)
    return trace


def _summary(trace_events, top):
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.telemetry import tracing
    report = tracing.segment_summary(trace_events, top=top)
    snap = telemetry.registry().snapshot()
    report["metrics"] = snap
    report["flush_causes"] = {
        s["labels"]["cause"]: s["value"]
        for s in snap.get("graft_engine_flushes_total",
                          {"samples": []})["samples"]}
    report["kvstore_bytes"] = {
        k.replace("graft_kvstore_", "").replace("_total", ""): v
        for k, v in telemetry.compact_snapshot().items()
        if k.startswith("graft_kvstore_")}
    report["device_memory"] = [
        dict(s["labels"], bytes=s["value"])
        for s in snap.get("graft_device_memory_bytes",
                          {"samples": []})["samples"]]
    return report


def _render_text(report):
    lines = ["graftscope summary", "=" * 60]
    lines.append("top segments by flush time (%d total):"
                 % report["segments_total"])
    lines.append("%-8s %-12s %6s %12s %6s %s"
                 % ("segment", "cause", "nodes", "dur(us)", "cache",
                    "device_time"))
    for s in report["top_segments"]:
        lines.append("%-8s %-12s %6s %12.1f %6s %s"
                     % (s["segment"], s["cause"], s["nodes"],
                        s["duration_us"], s["cache"], s["device_time"]))
    lines.append("")
    lines.append("flush time by cause (us): %s"
                 % json.dumps(report["flush_causes_us"]))
    lines.append("flush counts by cause:    %s"
                 % json.dumps(report["flush_causes"]))
    lines.append("kvstore bytes:            %s"
                 % json.dumps(report["kvstore_bytes"]))
    lines.append("")
    lines.append("device memory:")
    for m in report["device_memory"]:
        lines.append("  %-24s %-8s %16d" % (m["device"], m["kind"],
                                            int(m["bytes"])))
    lines.append("")
    lines.append("full metrics snapshot: %d metric families"
                 % len(report["metrics"]))
    for k, v in sorted(report["metrics"].items()):
        lines.append("  %-40s %s (%d series)"
                     % (k, v["kind"], len(v["samples"])))
    return "\n".join(lines)


def selftest():
    """Trace a 3-op bulked program and validate the dump (lint tier).
    Returns a list of problems — empty means pass."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine, profiler
    from incubator_mxnet_tpu.telemetry import tracing

    fd, path = tempfile.mkstemp(suffix=".json", prefix="graftscope_self_")
    os.close(fd)
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")
    a = mx.nd.array(np.ones((8, 8), np.float32))
    with engine.bulk(16):
        b = a * a
        c = b + a
        d = c - a
        d.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    os.unlink(path)
    problems = tracing.validate_chrome_trace(trace)
    events = trace["traceEvents"]
    flows = [e for e in events if e.get("ph") in ("s", "f")]
    if not flows:
        problems.append("no flow events in the trace (record→flush links "
                        "are gone)")
    deferred = [e for e in events
                if e.get("args", {}).get("deferred") is True]
    if len(deferred) < 3:
        problems.append("expected >=3 deferred op records, got %d"
                        % len(deferred))
    segs = [e for e in events if e.get("name") == tracing.SEGMENT_SPAN]
    if not segs:
        problems.append("no bulk_segment_flush span")
    elif segs[0].get("args", {}).get("nodes") != 3:
        problems.append("segment span nodes=%r, expected 3"
                        % segs[0].get("args", {}).get("nodes"))
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.telemetry",
        description="graftscope: segment-aware tracing + metrics summary")
    ap.add_argument("--summary", action="store_true",
                    help="run (or load) a traced workload and report")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--trace", metavar="PATH",
                    help="summarize an existing chrome-trace dump instead "
                         "of running the demo step")
    ap.add_argument("--top", type=int,
                    default=int(os.environ.get("GRAFT_TELEMETRY_TOPK",
                                               "10")),
                    help="segment table size (GRAFT_TELEMETRY_TOPK)")
    ap.add_argument("--selftest", action="store_true",
                    help="trace a 3-op bulked program and validate the "
                         "dump (CI smoke tier)")
    args = ap.parse_args(argv)

    if args.selftest:
        problems = selftest()
        if problems:
            for p in problems:
                print("graftscope selftest FAIL: %s" % p, file=sys.stderr)
            return 1
        print("graftscope selftest OK (schema + flow links valid)")
        return 0

    if not args.summary:
        ap.print_help()
        return 2

    if args.trace:
        with open(args.trace) as f:
            events = json.load(f)["traceEvents"]
    else:
        events = _demo_training_step()["traceEvents"]
    report = _summary(events, args.top)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        print(_render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
