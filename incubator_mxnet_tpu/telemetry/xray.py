"""graftxray — in-program phase attribution + true device timestamps
for the compiled step.

graftstep (whole-step compilation) made the steady-state train step ONE
donated XLA program — and thereby opaque to graftlens: the per-step
decomposition that drives the autotuner and the straggler analytics
collapses to a single host-observed ``device_async`` span in compiled
mode.  This module reopens the program:

* **Phase provenance at trace time.**  ``step_compile.py`` threads
  ``jax.named_scope`` markers (``xray:forward``, ``xray:backward``,
  ``xray:update[bucket_i]``) through its trace, so every HLO op in the
  compiled program carries the phase in its ``op_name`` metadata —
  fusion keeps the representative op's scope, so the attribution
  survives XLA's optimizer.  :func:`scope_map_from_hlo` parses the
  OPTIMIZED HLO of the compiled executable (the names the profiler
  trace references) into an op→phase table, registered per program via
  :func:`note_program`.

* **On-demand capture.**  ``GRAFT_XRAY=1`` arms the harness (default
  off — the disabled path is one memoized env read per dispatch).
  Armed, a capture session runs ``jax.profiler`` around
  ``GRAFT_XRAY_STEPS`` (default 3) compiled dispatches, started by any
  of: ``GRAFT_XRAY_EVERY=N`` (periodic), :func:`request_capture`
  (manual / tests), a lens slow-step flag (wall > ``GRAFT_XRAY_SLOW_X``
  × the rolling median of compiled windows), or a watchdog trip on an
  aged compiled bracket.  The emitted chrome trace is parsed with the
  SAME core ``aggregate.ingest_xla`` uses offline (one parser, online +
  offline), device ops map back to phases by scope name, and the
  result feeds the lens ring, the metrics registry and the blackbox.

* **Exact-sum conservation.**  Durations accumulate as integer
  nanoseconds partitioned over phases: ``sum(phase device times) +
  unattributed == program device span`` holds EXACTLY for every
  capture (``conservation_ok`` is asserted by tests and the tier-12
  selftest) — the compiled-mode twin of the lens' six-component
  host-side contract.

* **Cost ledger.**  Each compiled program registers its
  ``jax.stages.Compiled.cost_analysis()`` / ``memory_analysis()``
  summary at trace time; retraces diff against the previous build of
  the same program, the diff journals to the blackbox
  (``xray_cost_diff``) and :func:`cost_regressions` hands EH301 storm
  reports a one-line "what got more expensive" summary.

CLI: ``python -m incubator_mxnet_tpu.telemetry --xray [DUMP]`` renders
capture sessions (live, or from a blackbox dump);
``python -m incubator_mxnet_tpu.telemetry.xray --selftest`` is the
lint tier: capture a 3-step compiled loop, assert phase rows +
conservation.
"""
from __future__ import annotations

import gzip
import json
import os
import re
import shutil
import tempfile
import threading
import time
import weakref
from collections import deque

import jax

from . import blackbox as _blackbox
from . import lens as _lens
from . import metrics as _metrics

__all__ = [
    "armed", "capture_every", "capture_steps", "request_capture",
    "dispatch_begin", "dispatch_end", "sessions", "reset",
    "note_program", "cost_regressions", "cost_history",
    "scope_map_from_hlo", "attribute", "parse_trace",
    "merge_intervals", "device_pids", "is_device_event", "step_spans",
    "step_rows", "load_trace", "find_trace_file",
    "DEVICE_PID_HINTS", "selftest", "main",
]


# ---------------------------------------------------------------------------
# env gating — memoized raw-string reads (the lens hot-path pattern):
# the disabled cost per dispatch is one os.environ lookup + one string
# identity compare, which is what the bench_eager xray_overhead gate
# holds under 2%
# ---------------------------------------------------------------------------

_OFF_VALUES = ("", "0", "false", "no", "off")
_armed_memo = ["\x00", False]
_every_memo = ["\x00", 0]


def armed():
    """GRAFT_XRAY (default off): is the capture harness armed?  Armed
    means triggers are LIVE (periodic, manual, slow-step, watchdog) —
    it does not by itself capture anything."""
    raw = os.environ.get("GRAFT_XRAY", "")
    if raw != _armed_memo[0]:
        _armed_memo[1] = raw.strip().lower() not in _OFF_VALUES
        _armed_memo[0] = raw
    return _armed_memo[1]


def capture_every():
    """GRAFT_XRAY_EVERY=N (default 0 = off): start a capture session on
    every N-th compiled dispatch."""
    raw = os.environ.get("GRAFT_XRAY_EVERY", "")
    if raw != _every_memo[0]:
        try:
            _every_memo[1] = max(int(raw), 0)
        except ValueError:
            _every_memo[1] = 0
        _every_memo[0] = raw
    return _every_memo[1]


def capture_steps():
    """GRAFT_XRAY_STEPS (default 3): compiled dispatches per session."""
    try:
        return max(int(os.environ.get("GRAFT_XRAY_STEPS", "3")), 1)
    except ValueError:
        return 3


_slow_memo = ["\x00", 3.0]


def _slow_factor():
    """GRAFT_XRAY_SLOW_X (default 3.0): a compiled lens window slower
    than this multiple of the rolling median requests a one-shot
    capture.  Memoized on the raw string — this runs on every armed
    compiled lens record."""
    raw = os.environ.get("GRAFT_XRAY_SLOW_X", "")
    if raw != _slow_memo[0]:
        try:
            _slow_memo[1] = max(float(raw or "3.0"), 1.0)
        except ValueError:
            _slow_memo[1] = 3.0
        _slow_memo[0] = raw
    return _slow_memo[1]


# ---------------------------------------------------------------------------
# shared trace-parsing core — ONE parser for the online capture path
# (this module) and the offline ``telemetry --ingest-xla`` CLI
# (aggregate.ingest_xla delegates here); same interval union, same
# ``_row`` step-window convention
# ---------------------------------------------------------------------------

DEVICE_PID_HINTS = ("tpu", "gpu", "/device:", "accelerator")


def merge_intervals(ivs):
    """Union of (t0, t1) intervals: (merged list, total covered)."""
    if not ivs:
        return [], 0.0
    ivs = sorted(ivs)
    out = [list(ivs[0])]
    for t0, t1 in ivs[1:]:
        if t0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t1)
        else:
            out.append([t0, t1])
    return out, sum(t1 - t0 for t0, t1 in out)


def device_pids(events):
    """Device-named process tracks from the metadata stream."""
    pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pname = str((ev.get("args") or {}).get("name", "")).lower()
            if any(h in pname for h in DEVICE_PID_HINTS):
                pids.add(ev.get("pid"))
    return pids


def is_device_event(ev, dpids):
    """Does this complete ("X") span represent DEVICE execution?  Four
    signals, any one suffices: our own sync-mode spans carry
    ``args.device_time``; XLA profiler traces put device ops on
    device-named process tracks; a ``cat`` naming the device; an
    ``args.hlo_op``/``hlo_module`` stamp (the XLA op stream — on the
    CPU backend these land on a '/host:CPU' track that the pid hints
    alone would miss)."""
    args = ev.get("args") or {}
    if args.get("device_time"):
        return True
    if "hlo_op" in args or "hlo_module" in args:
        return True
    if ev.get("pid") in dpids:
        return True
    pid = str(ev.get("pid", "")).lower()
    cat = str(ev.get("cat", "")).lower()
    return any(h in pid for h in DEVICE_PID_HINTS) or "device" in cat


def load_trace(path_or_doc):
    """Chrome-trace events from a path (``.json`` or ``.json.gz``), a
    parsed dict, or a bare event list."""
    doc = path_or_doc
    if isinstance(path_or_doc, str):
        opener = gzip.open if path_or_doc.endswith(".gz") else open
        with opener(path_or_doc, "rt") as f:
            doc = json.load(f)
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("not a chrome trace: no traceEvents list")
    return events


def find_trace_file(logdir):
    """Newest ``*.trace.json[.gz]`` under a ``jax.profiler.start_trace``
    log directory (``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``),
    or None."""
    best = None
    for root, _dirs, files in os.walk(logdir):
        for name in files:
            if name.endswith(".trace.json") or name.endswith(
                    ".trace.json.gz"):
                p = os.path.join(root, name)
                if best is None or os.path.getmtime(p) > \
                        os.path.getmtime(best):
                    best = p
    return best


def step_spans(events):
    """Group device-busy spans by their ``args.step`` stamp (None pools
    the unstamped).  Returns ``(by_step, n_device, dpids)`` —
    ``by_step`` maps step id → [(t0, t1), ...] in seconds."""
    dpids = device_pids(events)
    by_step = {}
    n_device = 0
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if not is_device_event(ev, dpids):
            continue
        n_device += 1
        t0 = float(ev["ts"]) * 1e-6
        t1 = t0 + float(ev["dur"]) * 1e-6
        step = (ev.get("args") or {}).get("step")
        if step is not None:
            try:        # externally produced traces stamp steps as
                step = int(step)    # strings — normalize so "7" and 7
            except (TypeError, ValueError):     # pool together
                pass
        by_step.setdefault(step, []).append((t0, t1))
    return by_step, n_device, dpids


def step_rows(by_step):
    """The device-ledger row convention shared by ``--ingest-xla`` and
    the online capture sessions: per-step busy unions, step windows
    chained previous-end → this-end (so ``busy_s + idle_s == wall_s``
    holds exactly per row, the live-lens contract), and a UNION total
    (not a sum — the pooled unattributed row's window overlaps the
    stamped rows').  Returns ``(rows, nonmono, total)``."""
    nonmono = []

    def _row(step, w0):
        merged, busy = merge_intervals(by_step[step])
        if w0 is None:
            w0 = merged[0][0]
        w1 = merged[-1][1]
        if w1 < w0:
            # id order disagrees with time order (a restarted step
            # counter, a merged multi-capture): the chained window start
            # sits past every span of this step, so wall/busy clamp to
            # 0 — real device time vanishes from the row.  Surface it
            # instead of zeroing silently
            nonmono.append(step)
        wall = max(w1 - w0, 0.0)
        busy = min(busy, wall)
        return {"step": step, "wall_s": round(wall, 6),
                "busy_s": round(busy, 6),
                "idle_s": round(wall - busy, 6),
                "busy_fraction": round(busy / wall, 4) if wall > 0
                else 0.0,
                "spans": len(by_step[step])}, w1

    rows = []
    # non-numeric stamps sort after numeric ones (never against them —
    # a mixed int/str sort would TypeError)
    stamped = sorted((s for s in by_step if s is not None),
                     key=lambda s: (1, str(s)) if isinstance(s, str)
                     else (0, s))
    prev_end = None
    for step in stamped:
        row, prev_end = _row(step, prev_end)
        rows.append(row)
    if None in by_step:
        rows.append(_row(None, None)[0])
    if by_step:
        merged, total_busy = merge_intervals(
            [sp for spans in by_step.values() for sp in spans])
        total_wall = merged[-1][1] - merged[0][0]
        total_busy = min(total_busy, total_wall)
    else:
        total_wall = total_busy = 0.0
    total = {"wall_s": round(total_wall, 6),
             "busy_s": round(total_busy, 6),
             "idle_s": round(total_wall - total_busy, 6),
             "busy_fraction": round(total_busy / total_wall, 4)
             if total_wall > 0 else 0.0}
    return rows, nonmono, total


# ---------------------------------------------------------------------------
# scope maps — op→phase tables parsed from the OPTIMIZED HLO of a
# compiled program.  The profiler's chrome trace names events after
# post-fusion HLO ops (``args.hlo_op``) and does NOT carry the
# named_scope strings; the scopes live in each op's metadata
# ``op_name`` path, which the executable's ``as_text()`` preserves.
# ---------------------------------------------------------------------------

# parens and whitespace are excluded: XLA wraps DERIVED ops' op_name
# paths in call syntax ("transpose(.../xray:forward)"), and a token
# class admitting ")" would mint a spurious "forward)" phase next to
# "forward"
_SCOPE_TOKEN = re.compile(r"xray:([^/\"\\()\s]+)")
_HLO_META = re.compile(
    r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.\-]+)\s*=.*?"
    r"metadata=\{[^}]*op_name=\"([^\"]*)\"")


def phase_of(op_name_path):
    """First ``xray:<phase>`` token of an HLO op_name path, or None."""
    m = _SCOPE_TOKEN.search(op_name_path or "")
    return m.group(1) if m else None


def scope_map_from_hlo(hlo_text):
    """Parse ``metadata={op_name="..."}`` from optimized HLO text into
    ``{hlo_op_name: phase}`` (ops without an ``xray:`` scope are left
    out — they pool into "unattributed" at attribution time, which is
    what the conservation contract accounts for)."""
    out = {}
    for line in hlo_text.splitlines():
        m = _HLO_META.match(line)
        if not m:
            continue
        phase = phase_of(m.group(2))
        if phase is not None:
            out[m.group(1)] = phase
    return out


def _norm_module(name):
    """Trace ``args.hlo_module`` → registry key: strip the ``jit_``
    prefix and any ``.N`` uniquifier suffix."""
    name = str(name or "")
    if name.startswith("jit_"):
        name = name[4:]
    return re.sub(r"\.\d+$", "", name)


# ---------------------------------------------------------------------------
# program registry + cost ledger — step_compile.note_program() feeds it
# at trace time, captures resolve scope maps from it lazily
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_programs = {}              # name -> {"ref", "scope_map", "label", "at"}
_cost_history = {}          # name -> [cost dict, ...] (last few builds)
_cost_diffs = deque(maxlen=8)   # latest retrace diffs, newest last


def _cost_summary(compiled):
    """flops / bytes-accessed / peak-alloc estimates of one compiled
    executable (``jax.stages.Compiled``) — best-effort: backends that
    expose neither analysis yield an empty dict."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if "flops" in ca:
                out["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for field, key in (("temp_size_in_bytes", "temp_bytes"),
                           ("argument_size_in_bytes", "argument_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("generated_code_size_in_bytes", "code_bytes")):
            v = getattr(ma, field, None)
            if v is not None:
                out[key] = float(v)
    except Exception:
        pass
    return out


def diff_costs(old, new):
    """Per-field (old, new) pairs for fields that changed by more than
    0.5% (or appeared/disappeared) between two cost summaries."""
    out = {}
    for k in sorted(set(old) | set(new)):
        a, b = old.get(k), new.get(k)
        if a is None or b is None:
            out[k] = (a, b)
        elif abs(b - a) > 0.005 * max(abs(a), 1e-12):
            out[k] = (a, b)
    return out


def note_program(name, compiled, label=None):
    """Register one compiled program (called by ``CompiledStep`` at
    trace time).  Journals the cost summary to the blackbox
    (``xray_cost``), and — when a program of the same name was
    registered before (a retrace) — journals the per-field diff
    (``xray_cost_diff``) so EH301 storm reports can name what got more
    expensive, not just what churned."""
    costs = _cost_summary(compiled)
    with _reg_lock:
        prev = _cost_history.get(name, [])
        diff = diff_costs(prev[-1], costs) if prev else {}
        _cost_history.setdefault(name, []).append(dict(costs))
        del _cost_history[name][:-4]
        _programs[name] = {"ref": weakref.ref(compiled), "scope_map": None,
                           "label": label, "at": time.time()}
        if diff:
            _cost_diffs.append({"program": name, "diff": dict(diff),
                                "at": time.time()})
    if _blackbox.enabled():
        _blackbox.record("xray_cost", program=name, label=label, **costs)
        if diff:
            _blackbox.record(
                "xray_cost_diff", program=name,
                **{k: {"old": v[0], "new": v[1]} for k, v in diff.items()})
    return costs


def cost_history(name=None):
    """Registered cost summaries (per program, oldest first)."""
    with _reg_lock:
        if name is not None:
            return [dict(c) for c in _cost_history.get(name, [])]
        return {n: [dict(c) for c in cs] for n, cs in _cost_history.items()}


def cost_regressions():
    """One human line naming the latest retrace cost growth ('' when no
    retrace changed any cost field) — appended to EH301 storm reports."""
    with _reg_lock:
        diffs = list(_cost_diffs)
    parts = []
    for d in diffs[-3:]:
        grown = ["%s %.3g→%.3g" % (k, v[0], v[1])
                 for k, v in sorted(d["diff"].items())
                 if v[0] is not None and v[1] is not None and v[1] > v[0]]
        if grown:
            parts.append("%s: %s" % (d["program"], ", ".join(grown)))
    return "; ".join(parts)


def _scope_maps():
    """Resolve the registry into ``{program_name: {op: phase}}``,
    parsing each live executable's optimized HLO lazily (once per
    build) — captures pay the as_text() walk, idle-armed dispatches
    never do."""
    with _reg_lock:
        items = list(_programs.items())
    maps = {}
    for name, info in items:
        if info["scope_map"] is None:
            compiled = info["ref"]()
            if compiled is None:
                continue
            try:
                info["scope_map"] = scope_map_from_hlo(compiled.as_text())
            except Exception:
                info["scope_map"] = {}
        maps[name] = info["scope_map"]
    return maps


# ---------------------------------------------------------------------------
# attribution — the conservation-exact partition
# ---------------------------------------------------------------------------

def attribute(events, scope_maps=None, top_k=8):
    """Partition a capture's device ops over xray phases.

    Every device-op span lands in EXACTLY ONE bin — its scope's phase,
    or ``unattributed`` (scope-less ops, ops of unregistered programs)
    — and durations accumulate as integer nanoseconds, so::

        sum(phase device seconds) + unattributed == program device span

    holds exactly (``conservation_ok``).  The span here is the summed
    device-busy time of the capture; the union window rides along as
    ``span`` (true device-side t0/t1 in the trace timebase) and the
    shared step-row ledger as ``ledger``.
    """
    if scope_maps is None:
        scope_maps = _scope_maps()
    by_step, n_device, dpids = step_spans(events)
    phase_ns = {}
    op_ns = {}                  # (phase, op) -> [ns, count]
    module_ns = {}
    unattr_ns = 0
    total_ns = 0
    all_iv = []
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if not is_device_event(ev, dpids):
            continue
        args = ev.get("args") or {}
        ns = int(round(float(ev["dur"]) * 1000.0))   # trace dur is µs
        total_ns += ns
        t0 = float(ev["ts"]) * 1e-6
        all_iv.append((t0, t0 + float(ev["dur"]) * 1e-6))
        module = _norm_module(args.get("hlo_module"))
        op = str(args.get("hlo_op") or ev.get("name") or "")
        phase = scope_maps.get(module, {}).get(op) if module else None
        if module:
            module_ns[module] = module_ns.get(module, 0) + ns
        if phase is None:
            unattr_ns += ns
            key = (None, op)
        else:
            phase_ns[phase] = phase_ns.get(phase, 0) + ns
            key = (phase, op)
        cell = op_ns.setdefault(key, [0, 0])
        cell[0] += ns
        cell[1] += 1
    rows, nonmono, total = step_rows(by_step)
    top = sorted(op_ns.items(), key=lambda kv: -kv[1][0])[:top_k]
    merged, _busy = merge_intervals(all_iv)
    span = {"t0": merged[0][0], "t1": merged[-1][1]} if merged else None
    return {
        "device_events": n_device,
        "phases": {p: {"device_s": ns * 1e-9,
                       "share": ns / total_ns if total_ns else 0.0}
                   for p, ns in sorted(phase_ns.items())},
        "unattributed_s": unattr_ns * 1e-9,
        "program_device_s": total_ns * 1e-9,
        "conservation_ok": sum(phase_ns.values()) + unattr_ns == total_ns,
        "span": span,
        "modules": {m: ns * 1e-9 for m, ns in sorted(module_ns.items())},
        "top_ops": [{"op": op or "<unnamed>", "phase": ph,
                     "device_s": cell[0] * 1e-9, "count": cell[1]}
                    for (ph, op), cell in top],
        "ledger": {"steps": rows, "total": total,
                   "nonmonotonic_steps": sorted(nonmono, key=str)},
    }


def parse_trace(path_or_doc, scope_maps=None):
    """One-call offline twin of a live capture: load + attribute."""
    return attribute(load_trace(path_or_doc), scope_maps=scope_maps)


# ---------------------------------------------------------------------------
# capture sessions
# ---------------------------------------------------------------------------

_session_lock = threading.Lock()
_active = [None]                # the open session dict, or None
_pending = []                   # one-shot request reasons (FIFO, cap 4)
_dispatch_count = [0]
_sessions = deque(maxlen=16)    # completed session summaries
_trigger_installed = [False]
_recent_walls = deque(maxlen=64)


def request_capture(reason="manual"):
    """Arm a one-shot capture starting at the next compiled dispatch.
    No-op (returns False) when GRAFT_XRAY is off — the triggered paths
    (watchdog, slow-step) stay inert unless the user armed the
    harness."""
    if not armed():
        return False
    with _session_lock:
        if len(_pending) < 4 and reason not in _pending:
            _pending.append(reason)
    return True


_walls_median = [0.0, 0]        # cached rolling median, records-until-refresh


def _lens_trigger(rec):
    """Lens observer: flag a slow compiled step.  The rolling median of
    compiled train windows is the baseline; one outlier wall requests a
    one-shot capture (the capture then explains the NEXT steps — the
    profile of a recurring stall, not of the one that already
    passed).  The median is refreshed every 8 records, not per record —
    this observer rides EVERY armed compiled step, and a per-step
    sort of the 64-wall ring would show up in the <2% idle-armed
    budget; an up-to-8-records-stale baseline does not change what
    counts as a 3x outlier."""
    if not armed() or not rec.get("compiled"):
        return
    wall = rec.get("wall_s", 0.0)
    n = len(_recent_walls)
    if n >= 8:
        if _walls_median[1] <= 0:
            _walls_median[0] = sorted(_recent_walls)[n // 2]
            _walls_median[1] = 8
        else:
            _walls_median[1] -= 1
        med = _walls_median[0]
        if med > 0 and wall > _slow_factor() * med:
            request_capture("slow-step")
    _recent_walls.append(wall)


def _ensure_trigger():
    if not _trigger_installed[0]:
        _trigger_installed[0] = True
        _lens.add_observer(_lens_trigger)


def dispatch_begin():
    """Called by ``CompiledStep._dispatch`` before the programs run.
    Starts a profiler session when one is due (pending one-shot request,
    or the GRAFT_XRAY_EVERY cadence).  Off/idle cost: one memoized env
    read."""
    if not armed():
        return
    _ensure_trigger()
    _dispatch_count[0] += 1
    # lock-free fast path: nothing pending, no cadence due — the
    # common armed-idle dispatch never takes the lock (GIL-atomic list
    # reads; a request racing this check starts one dispatch later,
    # which the one-shot semantics already allow)
    if _active[0] is None and not _pending:
        n = capture_every()
        if n <= 0 or _dispatch_count[0] % n != 0:
            return
    with _session_lock:
        if _active[0] is not None:
            return
        reason = None
        if _pending:
            reason = _pending.pop(0)
        else:
            n = capture_every()
            if n > 0 and _dispatch_count[0] % n == 0:
                reason = "every-%d" % n
        if reason is None:
            return
        logdir = tempfile.mkdtemp(prefix="graft_xray_")
        try:
            jax.profiler.start_trace(logdir)
        except Exception as e:
            # another profiler owns the trace, or the backend refuses:
            # journal and stand down — capture failures never fail steps
            shutil.rmtree(logdir, ignore_errors=True)
            _blackbox.record("xray_capture", reason=reason, error=repr(e),
                             ok=False)
            return
        _active[0] = {"reason": reason, "dir": logdir, "steps": 0,
                      "want": capture_steps(), "t0": time.time()}


def dispatch_end(sync=None):
    """Called by ``CompiledStep._dispatch`` after write-back.  Counts
    the dispatch into the open session and closes it once it spans
    ``GRAFT_XRAY_STEPS`` dispatches — blocking on ``sync`` (the step's
    output arrays) first so the device work lands inside the trace."""
    if not armed():
        return
    if _active[0] is None:      # lock-free: no session open (sessions
        return                  # open/close on this thread only)
    with _session_lock:
        sess = _active[0]
        if sess is None:
            return
        sess["steps"] += 1
        if sess["steps"] < sess["want"]:
            return
        _active[0] = None
    _close_session(sess, sync)


def _close_session(sess, sync):
    report = None
    error = None
    try:
        if sync is not None:
            jax.block_until_ready(sync)
    except Exception:
        pass
    try:
        jax.profiler.stop_trace()
    except Exception as e:
        error = repr(e)
    if error is None:
        try:
            path = find_trace_file(sess["dir"])
            if path is None:
                error = "no trace file emitted under %s" % sess["dir"]
            else:
                report = attribute(load_trace(path))
        except Exception as e:
            error = repr(e)
    shutil.rmtree(sess["dir"], ignore_errors=True)
    summary = {
        "reason": sess["reason"],
        "steps": sess["steps"],
        "wall_s": round(time.time() - sess["t0"], 6),
        "at": time.time(),
        "ok": error is None and report is not None,
    }
    if error is not None:
        summary["error"] = error
    if report is not None:
        summary["report"] = report
    _sessions.append(summary)
    _publish(summary)
    return summary


def _publish(summary):
    report = summary.get("report")
    phases = {p: round(d["device_s"], 9)
              for p, d in (report or {}).get("phases", {}).items()}
    _blackbox.xray_session(
        summary["reason"], summary["steps"], phases,
        unattributed_s=round(report["unattributed_s"], 9)
        if report else None,
        program_device_s=round(report["program_device_s"], 9)
        if report else None,
        conservation_ok=report["conservation_ok"] if report else None,
        ok=summary["ok"], error=summary.get("error"),
        top_ops=[{"op": r["op"], "phase": r["phase"],
                  "device_us": round(r["device_s"] * 1e6, 3)}
                 for r in (report or {}).get("top_ops", [])[:5]])
    _metrics.xray_capture(summary["reason"], summary["ok"])
    if report:
        for p, d in report["phases"].items():
            _metrics.xray_phase_seconds(p, d["device_s"])
        _metrics.xray_phase_seconds("unattributed",
                                    report["unattributed_s"])
        _lens.attach_xray({
            "reason": summary["reason"],
            "phases": phases,
            "unattributed_s": round(report["unattributed_s"], 9),
            "program_device_s": round(report["program_device_s"], 9),
            "span": report["span"],
            "per_step_device_s":
                round(report["program_device_s"] / summary["steps"], 9)
                if summary["steps"] else 0.0,
        }, max_records=summary["steps"])


def sessions():
    """Completed capture-session summaries, oldest first (copies)."""
    with _session_lock:
        return [dict(s) for s in _sessions]


def capture_active():
    with _session_lock:
        return _active[0] is not None


def reset():
    """Drop harness state (tests): sessions, pending requests, the
    dispatch counter, the cost ledger and the program registry.  The
    lens observer stays installed (it is armed()-gated)."""
    with _session_lock:
        _active[0] = None
        del _pending[:]
        _dispatch_count[0] = 0
        _sessions.clear()
    with _reg_lock:
        _programs.clear()
        _cost_history.clear()
        _cost_diffs.clear()
    _recent_walls.clear()
    _walls_median[0] = 0.0
    _walls_median[1] = 0


# ---------------------------------------------------------------------------
# selftest (lint tier 12): capture a 3-step compiled loop, assert phase
# rows + exact conservation + idle-armed inertness
# ---------------------------------------------------------------------------

def selftest(verbose=False):
    """Returns a list of problems — empty means pass."""
    import numpy as np

    import incubator_mxnet_tpu as mx  # noqa: F401
    from ..gluon import Trainer
    from ..gluon import step_compile as sc

    problems = []
    saved = {k: os.environ.get(k)
             for k in ("GRAFT_XRAY", "GRAFT_XRAY_EVERY", "GRAFT_XRAY_STEPS")}
    os.environ["GRAFT_XRAY"] = "1"
    os.environ.pop("GRAFT_XRAY_EVERY", None)
    os.environ["GRAFT_XRAY_STEPS"] = "3"
    reset()
    try:
        net = sc._make_net("graftxray_", n_params=4, shape=(1, 5))
        sc._seed_params(net)
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.05, "momentum": 0.9},
                     kvstore=None)
        cstep = sc.CompiledStep(tr, net, enabled=True)
        rng = np.random.RandomState(11)

        def batch():
            return mx.nd.array(
                rng.uniform(0.5, 1.5, (6, 5)).astype(np.float32))

        # step 1 falls back + traces; steps 2-3 are compiled and armed
        # but idle — no session may open without a trigger
        for _ in range(3):
            cstep(batch())
        if cstep.compiled_steps < 2:
            problems.append("compiled path not reached (%d compiled)"
                            % cstep.compiled_steps)
        if sessions() or capture_active():
            problems.append("armed-but-idle dispatches opened a capture "
                            "session (triggers must be explicit)")
        if not cost_history():
            problems.append("no cost summaries registered at trace time")

        # triggered capture across 3 compiled dispatches
        if not request_capture("selftest"):
            problems.append("request_capture returned False while armed")
        for _ in range(4):
            cstep(batch())
        sess = sessions()
        if not sess:
            problems.append("no capture session completed after trigger")
        else:
            s = sess[-1]
            if not s["ok"]:
                problems.append("capture session failed: %s"
                                % s.get("error"))
            else:
                rep = s["report"]
                if verbose:
                    print(json.dumps(rep, indent=2, default=str))
                if not rep["conservation_ok"]:
                    problems.append(
                        "conservation violated: phases %.9fs + "
                        "unattributed %.9fs != span %.9fs"
                        % (sum(p["device_s"]
                               for p in rep["phases"].values()),
                           rep["unattributed_s"],
                           rep["program_device_s"]))
                if not rep["phases"]:
                    problems.append("no xray phases attributed (scope "
                                    "metadata missing from the trace?)")
                else:
                    names = set(rep["phases"])
                    if not any(n.startswith(("forward", "backward",
                                             "update")) for n in names):
                        problems.append("phases %r carry no step scopes"
                                        % sorted(names))
                if not rep["ledger"]["steps"]:
                    problems.append("shared parser produced no ledger "
                                    "rows")
                if s["steps"] != 3:
                    problems.append("session spanned %d dispatches "
                                    "(want 3)" % s["steps"])
        recs = [r for r in _lens.steps() if "xray" in r]
        if _lens.enabled() and sess and sess[-1]["ok"] and not recs:
            problems.append("capture did not annotate any lens window")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset()
    return problems


def main(argv=None):
    import argparse
    import sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.telemetry.xray",
        description="graftxray compiled-step phase attribution selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="capture a 3-step compiled loop; assert phase "
                         "rows + exact-sum conservation (CI tier 12)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.print_help()
        return 2
    problems = selftest(verbose=args.verbose)
    if problems:
        for p in problems:
            print("graftxray selftest FAIL: %s" % p, file=sys.stderr)
        return 1
    print("graftxray selftest OK (triggered 3-step capture, phase "
          "attribution conserved exactly, idle-armed dispatches inert)")
    return 0


if __name__ == "__main__":
    import sys
    # ``python -m …telemetry.xray`` loads this file TWICE (once as the
    # package submodule CompiledStep imports, once as __main__): run the
    # selftest in the CANONICAL copy so the registry/capture globals it
    # asserts on are the ones the instrumented step actually touched
    from incubator_mxnet_tpu.telemetry import xray as _canonical
    sys.exit(_canonical.main())
