"""graftwatch hang watchdog.

A background thread that times the flight recorder's in-flight brackets
(engine flushes, dist collectives, training phases/steps — see
:mod:`~incubator_mxnet_tpu.telemetry.blackbox`).  When a bracket stays
open longer than ``GRAFT_WATCHDOG_TIMEOUT`` seconds of wall clock, the
watchdog declares a hang and:

1. writes the flight-recorder dump (``reason="watchdog"``) naming the
   stuck bracket — for a stalled flush that is the segment id, cause and
   node count; for a stalled collective the path/keys/bytes/rank,
2. dumps every thread's stack via :mod:`faulthandler` to stderr (the
   crash-safe spelling; the JSON dump also embeds formatted stacks),
3. bumps ``graft_watchdog_trips_total`` and, when
   ``GRAFT_WATCHDOG_ABORT`` is set, kills the process with exit code 134
   so a supervisor restarts it instead of letting it hang forever.

The watchdog is OFF unless ``GRAFT_WATCHDOG_TIMEOUT`` is set to a
positive number of seconds (``maybe_start`` runs at telemetry import),
or :func:`start` is called explicitly.  Each open bracket trips at most
once; progress (any bracket closing) rearms the idle gauges.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time

from . import blackbox as _blackbox
from . import metrics as _metrics

__all__ = ["Watchdog", "start", "stop", "active", "maybe_start",
           "configured_timeout", "register_dead_nodes_provider"]

_ABORT_EXIT_CODE = 134          # 128 + SIGABRT, the classic watchdog code


def configured_timeout():
    """GRAFT_WATCHDOG_TIMEOUT in seconds, or None when unset/invalid."""
    raw = os.environ.get("GRAFT_WATCHDOG_TIMEOUT", "").strip()
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


def _abort_configured():
    return os.environ.get("GRAFT_WATCHDOG_ABORT", "").strip().lower() \
        in ("1", "true", "yes", "on")


def _escalate_configured():
    """GRAFT_WATCHDOG_ESCALATE: on trip, raise a typed error INTO the
    thread blocked on the stuck bracket (graftarmor fail-fast) instead
    of only dumping.  The raise lands at the next Python bytecode the
    thread executes — socket waits and lock waits surface it; a thread
    parked inside a C-level XLA collective does not return to bytecode,
    so for those GRAFT_WATCHDOG_ABORT remains the only hard stop
    (docs/robustness.md)."""
    return os.environ.get("GRAFT_WATCHDOG_ESCALATE", "").strip().lower() \
        in ("1", "true", "yes", "on")


# -- graftarmor: dead-rank attribution --------------------------------------

_dead_provider = [None]


def register_dead_nodes_provider(fn):
    """Install a callable returning the currently-dead worker ranks
    (DistKVStore registers its PS heartbeat table).  Queried at trip
    time only, in a sacrificial daemon thread — the provider may need a
    client lock HELD BY the very RPC that hung, so the watchdog must
    never call it synchronously."""
    _dead_provider[0] = fn


def _query_dead_ranks(timeout=2.0):
    fn = _dead_provider[0]
    if fn is None:
        return []
    box = []

    def _run():
        try:
            box.append(list(fn()))
        except Exception:
            pass

    t = threading.Thread(target=_run, daemon=True,
                         name="graftwatch-deadnodes")
    t.start()
    t.join(timeout)
    return box[0] if box else []


class Watchdog(threading.Thread):
    """The poller.  ``interval`` defaults to timeout/4 clamped to
    [50ms, 1s] so a trip lands within ~1.25x the configured timeout."""

    def __init__(self, timeout, interval=None, abort=None, path=None):
        super().__init__(name="graftwatch-watchdog", daemon=True)
        self.timeout = float(timeout)
        self.interval = interval if interval is not None \
            else min(max(self.timeout / 4.0, 0.05), 1.0)
        self.abort = _abort_configured() if abort is None else bool(abort)
        self.path = path
        self.trips = 0
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self.interval):
            self.poll()

    def stop(self):
        self._stop_evt.set()

    def poll(self, now=None):
        """One scan: refresh the graft_watchdog_* gauges, trip when
        brackets outlive the timeout.  The trip reports the NEWEST
        (innermost) expired bracket — a stalled collective inside a
        step opens step → phase → collective, and the innermost one is
        the thing actually stuck; the enclosing brackets expire with it
        and are marked tripped as part of the same incident (one dump
        per hang, not one per nesting level).  Split out for tests."""
        now = time.time() if now is None else now
        entries = _blackbox.inflight_entries()
        oldest_age = max((now - e["since"] for e in entries), default=0.0)
        progress_age = now - _blackbox.last_progress()["ts"]
        _metrics.watchdog_status(len(entries), oldest_age, progress_age)
        # async_pending brackets (graftlap reduces issued mid-backward)
        # are deliberately left open until their consumer waits — they
        # age only from _begin_wait's re-stamp, never from issue time
        expired = [e for e in entries
                   if now - e["since"] > self.timeout
                   and not e.get("tripped")
                   and not e.get("async_pending")]
        if expired:
            target = max(expired, key=lambda e: e["since"])   # innermost
            for e in expired:
                e["tripped"] = True
            self.trip(target, now - target["since"])

    def trip(self, entry, age):
        """Declare the hang: dump, stacks, metrics, then (optionally)
        escalate a typed error into the stuck thread and/or abort."""
        self.trips += 1
        detail = entry.get("detail") or {}
        dead = _query_dead_ranks()
        _blackbox.record("watchdog_trip", site=entry["site"],
                         detail=detail, age_s=round(age, 3),
                         timeout_s=self.timeout,
                         thread=entry.get("thread"),
                         dead_ranks=dead)
        _metrics.watchdog_trip(entry["site"])
        path = _blackbox.dump(
            path=self.path, reason="watchdog", extra={"watchdog": {
                "timeout_s": self.timeout,
                "tripped_site": entry["site"],
                "tripped_detail": detail,
                "tripped_thread": entry.get("thread"),
                "age_s": round(age, 3),
                "trips": self.trips,
                "abort": self.abort,
                "dead_ranks": dead,
            }})
        sys.stderr.write(
            "graftwatch: WATCHDOG TRIP — %r in flight for %.1fs "
            "(timeout %.1fs), detail=%r, dead_ranks=%r; dump: %s\n"
            % (entry["site"], age, self.timeout, detail, dead, path))
        # graftxray: an aged COMPILED bracket (a step_compile journal or
        # the compiled_step collective) requests a one-shot profiler
        # capture of the next dispatches — armed()-gated inside, so this
        # is inert unless GRAFT_XRAY is on
        if "compiled" in repr(detail):
            try:
                from . import xray as _xray
                _xray.request_capture("watchdog:%s" % entry["site"])
            except Exception:
                pass
        try:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            pass
        if _escalate_configured():
            self.escalate(entry, age, dead)
        if self.abort:
            sys.stderr.write("graftwatch: GRAFT_WATCHDOG_ABORT set — "
                             "exiting %d\n" % _ABORT_EXIT_CODE)
            os._exit(_ABORT_EXIT_CODE)

    def escalate(self, entry, age, dead_ranks=()):
        """Raise a typed hang error INTO the thread that opened the
        stuck bracket (graftarmor fail-fast): a ps_* bracket becomes
        :class:`~..armor.errors.PSUnavailableError`, any other
        collective :class:`~..armor.errors.CollectiveTimeoutError`,
        both naming the dead ranks.  Uses PyThreadState_SetAsyncExc,
        which instantiates the exception CLASS with no arguments — so
        the payload rides a dynamically-built zero-arg subclass.  The
        raise lands only when the target thread next executes Python
        bytecode (socket/lock waits: yes; C-blocked XLA: no — see
        GRAFT_WATCHDOG_ABORT).  Returns True if an escalation was
        delivered."""
        tid = entry.get("tid")
        if tid is None or entry.get("site") != "collective":
            return False
        from ..armor.errors import (CollectiveTimeoutError,
                                    PSUnavailableError)
        detail = entry.get("detail") or {}
        path = str(detail.get("path", ""))
        if path.startswith("ps_"):
            base, args = PSUnavailableError, (
                path, 0)
            kwargs = {"last_error": "watchdog trip after %.1fs" % age,
                      "dead_ranks": tuple(dead_ranks)}
        else:
            base, args = CollectiveTimeoutError, (
                path or entry["site"], round(age, 3), self.timeout)
            kwargs = {"dead_ranks": tuple(dead_ranks), "detail": detail}
        exc_cls = type(base.__name__, (base,), {
            "__init__": (lambda self, _b=base, _a=args, _k=kwargs:
                         _b.__init__(self, *_a, **_k)),
            "__module__": base.__module__,
        })
        import ctypes
        delivered = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), ctypes.py_object(exc_cls))
        if delivered > 1:       # hit more than one thread state: undo
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), None)
            return False
        if delivered == 1:
            _metrics.watchdog_escalation(path or entry["site"])
            _blackbox.record("watchdog_escalation", site=entry["site"],
                             path=path, tid=tid, error=base.__name__,
                             dead_ranks=list(dead_ranks))
            sys.stderr.write(
                "graftwatch: escalating %s into thread %d (path=%r, "
                "dead_ranks=%r)\n"
                % (base.__name__, tid, path, list(dead_ranks)))
            return True
        return False


_active = [None]


def active():
    """The running Watchdog instance, or None."""
    wd = _active[0]
    return wd if wd is not None and wd.is_alive() else None


def start(timeout=None, interval=None, abort=None, path=None):
    """Start (or replace) the watchdog thread.  ``timeout`` falls back
    to GRAFT_WATCHDOG_TIMEOUT; returns the Watchdog (None if no timeout
    is configured anywhere, or the flight recorder is disabled — the
    watchdog times the recorder's in-flight brackets, so GRAFT_BLACKBOX=0
    leaves it nothing to watch; warned, never silent)."""
    timeout = timeout if timeout is not None else configured_timeout()
    if timeout is None or timeout <= 0:
        return None
    if not _blackbox.enabled():
        import logging
        logging.getLogger("graftwatch").warning(
            "watchdog requested (timeout %.1fs) but the flight recorder "
            "is disabled (GRAFT_BLACKBOX=0) — the watchdog times the "
            "recorder's in-flight brackets, so it is NOT starting; "
            "re-enable the recorder to get hang protection", timeout)
        return None
    # signal/excepthook chains ride the same start path: a main-thread
    # start() installs them even if the first import ran on a worker
    # thread (where signal.signal is unavailable)
    _blackbox.install_hooks()
    stop()
    wd = Watchdog(timeout, interval=interval, abort=abort, path=path)
    _active[0] = wd
    wd.start()
    return wd


def stop():
    wd = _active[0]
    _active[0] = None
    if wd is not None:
        wd.stop()
        if wd.is_alive() and wd is not threading.current_thread():
            wd.join(timeout=2.0)
    return wd


def maybe_start():
    """Telemetry-import hook: run the watchdog iff the env asks for it
    (start() itself warns-and-declines when the recorder is off)."""
    if _active[0] is None and configured_timeout() is not None:
        return start()
    return None
