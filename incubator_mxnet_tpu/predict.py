"""Python side of the C predict API (src/predict/c_predict_api.cc).

The native MXPred* functions embed an interpreter and drive this module:
``create_predictor(symbol_json, param_bytes, input_shapes)`` returns an
object with set_input/forward/output_shape/output_bytes — a minimal
deployment surface mirroring the reference's c_predict_api.cc
PredictorObj.
"""
from __future__ import annotations

import json
import os
import struct
import tempfile

import numpy as np

__all__ = ["Predictor", "create_predictor"]


class Predictor(object):
    """One bound inference graph (ref: c_predict_api.cc PredictorObj)."""

    def __init__(self, symbol_json, param_bytes, input_shapes):
        from . import symbol as sym_mod
        from . import ndarray as nd
        from .context import cpu

        self._sym = sym_mod.load_json(symbol_json)
        # .params bytes → name → NDArray (arg:/aux: prefixes optional)
        with tempfile.NamedTemporaryFile(delete=False) as f:
            f.write(param_bytes)
            path = f.name
        try:
            loaded = nd.load(path)
        finally:
            os.unlink(path)
        arg_params, aux_params = {}, {}
        if isinstance(loaded, dict):
            for k, v in loaded.items():
                if k.startswith("arg:"):
                    arg_params[k[4:]] = v
                elif k.startswith("aux:"):
                    aux_params[k[4:]] = v
                else:
                    arg_params[k] = v
        self._input_shapes = {k: tuple(int(d) for d in v)
                              for k, v in input_shapes.items()}
        args = {}
        arg_shapes, _, aux_shapes = self._sym.infer_shape(
            **self._input_shapes)
        for name, shape in zip(self._sym.list_arguments(), arg_shapes):
            if name in arg_params:
                args[name] = arg_params[name]
            else:
                args[name] = nd.zeros(shape)
        aux = {}
        for name, shape in zip(self._sym.list_auxiliary_states(),
                               aux_shapes):
            aux[name] = (aux_params[name] if name in aux_params
                         else nd.zeros(shape))
        self._exe = self._sym.bind(cpu(), args, grad_req="null",
                                   aux_states=aux)
        self._outputs = []

    def set_input(self, key, data_bytes):
        arr = np.frombuffer(data_bytes, np.float32).reshape(
            self._input_shapes[key])
        from . import ndarray as nd
        self._exe.arg_dict[key]._write(
            nd.array(arr)._read().astype(
                self._exe.arg_dict[key]._read().dtype))
        return True

    def forward(self):
        self._outputs = self._exe.forward(is_train=False)
        return True

    def output_shape(self, index):
        return tuple(int(d) for d in self._outputs[index].shape)

    def output_bytes(self, index):
        return np.ascontiguousarray(
            self._outputs[index].asnumpy().astype(np.float32)).tobytes()


def create_predictor(symbol_json, param_bytes, input_shapes):
    """Entry point called from the C shim (MXPredCreate)."""
    return Predictor(symbol_json, param_bytes, input_shapes)
