"""Python side of the C predict API (src/predict/c_predict_api.cc).

The native MXPred* functions embed an interpreter and drive this module:
``create_predictor(symbol_json, param_bytes, input_shapes)`` returns an
object with set_input/forward/output_shape/output_bytes — a minimal
deployment surface mirroring the reference's c_predict_api.cc
PredictorObj.

Rebased onto graftserve (PR 11): the param bytes are parsed IN MEMORY
by ``nd.load_buffer`` (no temp-file round trip) and the model registers
into the process-wide serving :class:`~incubator_mxnet_tpu.serving.ModelRegistry`
— the legacy C ABI and the serving runtime share ONE loader and one
residency accounting, and ``forward`` is one compiled dispatch (a
``CachedOp``-style jitted graph) instead of the per-op executor replay.
"""
from __future__ import annotations

import itertools

import numpy as np

__all__ = ["Predictor", "create_predictor"]

_predictor_ids = itertools.count(1)


class Predictor(object):
    """One bound inference graph (ref: c_predict_api.cc PredictorObj),
    served through a graftserve registry handle."""

    def __init__(self, symbol_json, param_bytes, input_shapes):
        from .serving import default_registry
        self._input_shapes = {k: tuple(int(d) for d in v)
                              for k, v in input_shapes.items()}
        self._name = "cpredict/%d" % next(_predictor_ids)
        self._registry = default_registry()
        # shared loader: nd.load_buffer parse + zeros for uncovered
        # arguments (serving/loader.bytes_model — the C-predict contract)
        self._handle = self._registry.load_bytes(
            self._name, symbol_json, param_bytes, self._input_shapes)
        # executor-bind dtype semantics: the C surface always hands f32
        # buffers, and the old bind cast them to the model's dtype (an
        # f16 .params payload computed in f16).  Mirror that: when the
        # float params agree on one dtype, inputs cast to it.
        _entry, params, _v = self._handle.acquire()
        fdtypes = {np.dtype(v.dtype) for v in params.values()
                   if np.dtype(v.dtype).kind == "f"}
        self._input_dtype = fdtypes.pop() if len(fdtypes) == 1 \
            else np.dtype(np.float32)
        self._inputs = {}
        self._outputs = []

    def set_input(self, key, data_bytes):
        self._inputs[key] = np.frombuffer(data_bytes, np.float32).reshape(
            self._input_shapes[key]).astype(self._input_dtype)
        return True

    def forward(self):
        # an input never set_input()-ed runs as zeros — the executor-bind
        # contract of the original C surface (bind filled nd.zeros)
        vals = [self._inputs.get(name)
                if self._inputs.get(name) is not None
                else np.zeros(self._input_shapes[name], self._input_dtype)
                for name in self._handle.input_names]
        out = self._handle.predict(*vals)
        self._outputs = list(out) if isinstance(out, tuple) else [out]
        return True

    def output_shape(self, index):
        return tuple(int(d) for d in self._outputs[index].shape)

    def output_bytes(self, index):
        return np.ascontiguousarray(
            np.asarray(self._outputs[index]).astype(np.float32)).tobytes()

    def __del__(self):
        try:
            self._registry.unload(self._name)
        except Exception:
            pass        # interpreter teardown


def create_predictor(symbol_json, param_bytes, input_shapes):
    """Entry point called from the C shim (MXPredCreate)."""
    return Predictor(symbol_json, param_bytes, input_shapes)
