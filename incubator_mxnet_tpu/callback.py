"""Training callbacks (ref: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint a Module every `period` epochs (ref: callback.py
    module_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Checkpoint params every `period` epochs (ref: callback.py:55)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    """ref: callback.py log_train_metric."""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer(object):
    """Throughput logger: every ``frequent`` batches, report samples/sec
    over the window just completed, plus current metric values
    (ref: callback.py:120 class Speedometer — same batch_end_callback
    contract, re-implemented around a window-start timestamp).
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = max(1, int(frequent))
        self.auto_reset = auto_reset
        self._window_start = None     # (nbatch, wall time) at window open
        self._pending = 0

    def _metrics_text(self, metric):
        if metric is None:
            return ""
        pairs = metric.get_name_value()
        if self.auto_reset:
            metric.reset()
        return "".join(" %s=%.6f" % nv for nv in pairs)

    def __call__(self, param):
        now = time.time()
        if self._window_start is None or param.nbatch < self._pending:
            # first batch of an epoch (or restart): open a fresh window
            self._window_start = (param.nbatch, now)
            self._pending = param.nbatch
            return
        self._pending = param.nbatch
        start_batch, start_time = self._window_start
        if param.nbatch - start_batch < self.frequent:
            return
        elapsed = max(now - start_time, 1e-9)
        rate = (param.nbatch - start_batch) * self.batch_size / elapsed
        logging.info("epoch %d batch %d: %.2f samples/sec%s",
                     param.epoch, param.nbatch, rate,
                     self._metrics_text(param.eval_metric))
        self._window_start = (param.nbatch, now)


class ProgressBar(object):
    """ref: callback.py class ProgressBar."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback(object):
    """ref: callback.py class LogValidationMetricsCallback."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
