"""Version-compatibility shims over the installed jax.

The codebase targets the modern ``jax.shard_map`` surface (keyword
``check_vma``); older jax releases only ship
``jax.experimental.shard_map.shard_map`` whose equivalent keyword is
``check_rep``.  Route every caller through here so the rest of the tree
can use one spelling regardless of the installed version.
"""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                      # jax < 0.6: experimental API
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
