"""Python side of the C train API (src/train/c_train_api.cc).

The native MXTrainer* functions embed an interpreter and drive this
module — the TPU rebuild's answer to the reference's C++ training
surface (ref: cpp-package/include/mxnet-cpp/: Symbol/Executor/Optimizer
driven from C++; all of the reference's non-Python bindings sit on one C
ABI, SURVEY §1 layer 10).  ``create_trainer`` binds a Module for
training; each ``step`` is forward + backward + optimizer update on the
currently set inputs, returning the batch loss.
"""
from __future__ import annotations

import json
import os
import struct
import tempfile

import numpy as np

__all__ = ["CTrainer", "create_trainer"]


class CTrainer(object):
    """One bound training graph driven through the C ABI."""

    def __init__(self, symbol_json, input_shapes, optimizer="sgd",
                 optimizer_params=None, param_bytes=None):
        from . import symbol as sym_mod
        from . import module as mod_mod
        from . import initializer
        from .context import cpu

        self._sym = sym_mod.load_json(symbol_json)
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in input_shapes.items()}
        label_names = [k for k in shapes if k.endswith("label")]
        data_names = [k for k in shapes if k not in label_names]
        self._mod = mod_mod.Module(self._sym, data_names=data_names,
                                   label_names=label_names or None,
                                   context=cpu())
        self._mod.bind(
            data_shapes=[(k, shapes[k]) for k in data_names],
            label_shapes=[(k, shapes[k]) for k in label_names] or None,
            for_training=True)
        if param_bytes:
            arg_params, aux_params = self._load_params(param_bytes)
            self._mod.init_params(initializer.Xavier(), arg_params=arg_params,
                                  aux_params=aux_params,
                                  allow_missing=True)
        else:
            self._mod.init_params(initializer.Xavier(magnitude=2.0))
        self._mod.init_optimizer(
            optimizer=optimizer,
            optimizer_params=json.loads(optimizer_params)
            if isinstance(optimizer_params, str) else (optimizer_params or
                                                       {"learning_rate": 0.01}))
        self._inputs = {}
        self._data_names = data_names
        self._label_names = label_names
        self._shapes = shapes

    @staticmethod
    def _load_params(param_bytes):
        from . import ndarray as nd
        with tempfile.NamedTemporaryFile(delete=False) as f:
            f.write(param_bytes)
            path = f.name
        try:
            loaded = nd.load(path)
        finally:
            os.unlink(path)
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k.split(":", 1)[-1]] = v
        return arg_params, aux_params

    # -- C ABI surface -----------------------------------------------------
    def set_input(self, key, data_bytes):
        shape = self._shapes[key]
        arr = np.frombuffer(data_bytes, np.float32).reshape(shape)
        self._inputs[key] = arr.copy()

    def step(self):
        """forward + backward + update on the staged inputs; returns the
        mean cross-entropy of the head output against the first label
        (the reference's SoftmaxOutput convention: the op emits
        probabilities, the gradient is p - onehot)."""
        from . import io as mio
        from . import ndarray as nd

        data = [nd.array(self._inputs[k]) for k in self._data_names]
        label = [nd.array(self._inputs[k]) for k in self._label_names]
        batch = mio.DataBatch(data=data, label=label)
        self._mod.forward(batch, is_train=True)
        self._mod.backward()
        self._mod.update()
        out = self._mod.get_outputs()[0].asnumpy()
        if self._label_names:
            y = self._inputs[self._label_names[0]].astype(np.int64).ravel()
            p = out.reshape(len(y), -1)
            eps = 1e-12
            return float(-np.mean(np.log(p[np.arange(len(y)), y] + eps)))
        return float(out.mean())

    def forward(self):
        """Inference forward on the staged inputs (no update)."""
        from . import io as mio
        from . import ndarray as nd
        data = [nd.array(self._inputs[k]) for k in self._data_names]
        batch = mio.DataBatch(data=data, label=None)
        self._mod.forward(batch, is_train=False)
        return 0

    def output_shape(self, index):
        return tuple(int(d) for d in
                     self._mod.get_outputs()[index].shape)

    def output_bytes(self, index):
        return self._mod.get_outputs()[index].asnumpy().astype(
            np.float32).tobytes()

    def save_params(self):
        """Serialized .params bytes (MXNet binary, arg:/aux: prefixed)."""
        from . import ndarray as nd
        arg_params, aux_params = self._mod.get_params()
        save_dict = {"arg:%s" % k: v for k, v in arg_params.items()}
        save_dict.update({"aux:%s" % k: v for k, v in aux_params.items()})
        with tempfile.NamedTemporaryFile(delete=False) as f:
            path = f.name
        try:
            nd.save(path, save_dict)
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)


def create_trainer(symbol_json, input_shapes, optimizer, optimizer_params,
                   param_bytes):
    return CTrainer(symbol_json, input_shapes, optimizer=optimizer,
                    optimizer_params=optimizer_params or None,
                    param_bytes=param_bytes or None)


class CDataIter(object):
    """One data iterator driven through the C ABI (the role of the
    reference's MXDataIterCreateIter/MXDataIterNext C API family,
    c_api.cc — here over the Python io registry, same layering as
    CTrainer)."""

    def __init__(self, it):
        self._it = it
        self._batch = None
        self._cache = {}

    def next(self):
        self._cache.clear()
        try:
            self._batch = next(self._it)
            return 1
        except StopIteration:
            self._batch = None
            return 0

    def reset(self):
        self._cache.clear()
        self._it.reset()

    def _arr(self, which, index):
        # the C ABI fetches bytes then shape per batch part: cache the
        # converted array so each part materializes once per batch
        key = (which, index)
        got = self._cache.get(key)
        if got is None:
            arrs = self._batch.data if which == "data" \
                else self._batch.label
            got = arrs[index].asnumpy().astype(np.float32)
            self._cache[key] = got
        return got

    def data_bytes(self, index=0):
        return self._arr("data", index).tobytes()

    def label_bytes(self, index=0):
        return self._arr("label", index).tobytes()

    def data_shape(self, index=0):
        return tuple(int(d) for d in self._arr("data", index).shape)

    def label_shape(self, index=0):
        return tuple(int(d) for d in self._arr("label", index).shape)


_C_ITER_FACTORIES = ("ImageRecordIter", "CSVIter", "MNISTIter",
                     "LibSVMIter")


def create_data_iter(name, params_json):
    """Factory by registered iterator name + JSON kwargs — the C ABI's
    MXDataIterCreate.  JSON lists become tuples (shape arguments)."""
    from . import io as mio
    if name not in _C_ITER_FACTORIES:
        raise ValueError("unknown data iter %r (have %s)"
                         % (name, ", ".join(_C_ITER_FACTORIES)))
    kwargs = json.loads(params_json) if params_json else {}
    kwargs = {k: tuple(v) if isinstance(v, list) else v
              for k, v in kwargs.items()}
    return CDataIter(getattr(mio, name)(**kwargs))


class CMetric(object):
    """One EvalMetric driven through the C ABI (MXMetric*)."""

    def __init__(self, name):
        from . import metric as metric_mod
        self._m = metric_mod.create(name)

    def update(self, label_bytes, label_shape, pred_bytes, pred_shape):
        from . import ndarray as nd
        label = np.frombuffer(label_bytes, np.float32).reshape(
            tuple(label_shape))
        pred = np.frombuffer(pred_bytes, np.float32).reshape(
            tuple(pred_shape))
        self._m.update([nd.array(label)], [nd.array(pred)])

    def get(self):
        return float(self._m.get()[1])

    def reset(self):
        self._m.reset()


def create_metric(name):
    return CMetric(name)
