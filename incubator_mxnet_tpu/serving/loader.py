"""graftserve model loaders — ONE place model bytes become a pure
jittable forward.

Every serving source funnels to the same ``(fn, param_vals,
input_names)`` triple:

* ``fn(param_vals, *input_vals)`` — a pure function of raw arrays,
  jit-compiled ONCE per (model, shape-bucket) signature by the registry
  (the paper's defining idea #3: Gluon hybridization → ``CachedOp``;
  here XLA's compile cache IS the signature cache, the TVM-style
  deployment-runtime split around a compiled graph),
* ``param_vals`` — name → raw array, the weight-residency unit the
  registry budgets/evicts/hot-swaps,
* ``input_names`` — positional input order of ``fn``.

Sources: a :class:`~incubator_mxnet_tpu.gluon.HybridBlock`
(``functionalize``, the CachedOp trace), a bound ``Module`` or a raw
``Symbol`` (``symbol_serving_fn`` over ``Symbol.eval_dict`` — the ops
trace through the same jax level), and the legacy C-predict payload
(symbol JSON + ``.params`` bytes) parsed IN MEMORY by
``nd.load_buffer`` — the loader ``predict.Predictor`` now shares, so
the C ABI surface and graftserve load weights identically.
"""
from __future__ import annotations

import numpy as np

__all__ = ["split_arg_aux", "load_params_bytes", "symbol_serving_fn",
           "symbol_model", "block_model", "module_model", "bytes_model"]


def split_arg_aux(loaded):
    """Split an ``nd.load``/``nd.load_buffer`` dict into (arg_params,
    aux_params), honoring the optional ``arg:``/``aux:`` name prefixes
    (ref: python/mxnet/model.py load_checkpoint)."""
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_params_bytes(param_bytes):
    """``.params`` bytes → (arg_params, aux_params) name→NDArray dicts,
    parsed in memory (``nd.load_buffer`` — no temp-file round trip)."""
    from ..ndarray import load_buffer
    loaded = load_buffer(param_bytes)
    if not isinstance(loaded, dict):
        raise ValueError("serving params must be a named .params payload "
                         "(got an unnamed array list)")
    return split_arg_aux(loaded)


def symbol_serving_fn(sym, input_names):
    """The pure inference forward of a Symbol: ``fn(param_vals,
    *input_vals)`` evaluating the graph under a jit trace (ops dispatch
    at the jax level, exactly like the CachedOp trace), with recording
    and training off.  Outputs: one raw array, or a tuple for
    multi-output symbols."""
    input_names = list(input_names)

    def fn(param_vals, *input_vals):
        from .. import autograd
        from ..ndarray import NDArray
        merged = {n: NDArray(v) for n, v in param_vals.items()}
        for n, v in zip(input_names, input_vals):
            merged[n] = NDArray(v)
        with autograd._scope(recording=False, training=False):
            out = sym.eval_dict(merged)
        outs = out if isinstance(out, list) else [out]
        vals = tuple(o._read() for o in outs)
        return vals[0] if len(vals) == 1 else vals

    return fn


def _raw(v):
    """NDArray/np/jax array → raw jax-compatible array value."""
    from ..ndarray import NDArray
    if isinstance(v, NDArray):
        return v._read()
    import jax.numpy as jnp
    return jnp.asarray(v)


def symbol_model(sym, params, input_shapes=None, input_names=None):
    """A Symbol + explicit params.  ``input_shapes`` (name→shape) or
    ``input_names`` designate the data inputs; arguments covered by
    neither get ZERO values of their inferred shapes (the C-predict
    contract: missing params default to zeros).  Returns ``(fn,
    param_vals, input_names)``."""
    params = {k: _raw(v) for k, v in params.items()}
    if input_names is None:
        if input_shapes:
            input_names = list(input_shapes.keys())
        else:
            input_names = [n for n in sym.list_arguments()
                           if n not in params]
    input_names = list(input_names)
    missing = [n for n in sym.list_arguments() + sym.list_auxiliary_states()
               if n not in params and n not in input_names]
    if missing:
        if not input_shapes:
            raise ValueError(
                "symbol arguments %r are neither params nor inputs; pass "
                "input_shapes so their shapes can be inferred" % missing)
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in input_shapes.items()}
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        inferred = dict(zip(sym.list_arguments(), arg_shapes))
        inferred.update(zip(sym.list_auxiliary_states(), aux_shapes))
        import jax.numpy as jnp
        for n in missing:
            params[n] = jnp.zeros(inferred[n], np.float32)
    return symbol_serving_fn(sym, input_names), params, input_names


def bytes_model(symbol_json, param_bytes, input_shapes):
    """The legacy C-predict payload: symbol JSON + ``.params`` bytes +
    input shapes (ref: c_predict_api.cc MXPredCreate).  One in-memory
    parse, zeros for uncovered arguments — the loader ``Predictor``
    rides."""
    from .. import symbol as sym_mod
    sym = sym_mod.load_json(symbol_json)
    arg_params, aux_params = load_params_bytes(param_bytes)
    params = dict(arg_params)
    params.update(aux_params)
    return symbol_model(sym, params, input_shapes=input_shapes)


def block_model(block, example, train=False):
    """A (preferably hybridized) HybridBlock: the CachedOp-style
    functionalized trace (``HybridBlock.serving_fn``).  ``example`` is
    one example input (or tuple of inputs) used to resolve deferred
    shapes.  Returns ``(fn, param_vals, input_names)`` — fn takes the
    inputs positionally."""
    from ..ndarray import NDArray
    if not isinstance(example, (list, tuple)):
        example = (example,)
    example = [e if isinstance(e, NDArray) else NDArray(_raw(e))
               for e in example]
    fn, param_vals = block.serving_fn(*example, train=train)
    input_names = ["input%d" % i for i in range(len(example))]
    return fn, param_vals, input_names


def module_model(module):
    """A bound, initialized ``Module`` — ``BaseModule.serving_fn``."""
    return module.serving_fn()
