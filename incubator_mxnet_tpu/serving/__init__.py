"""graftserve — the production serving runtime (the ROADMAP
"millions-of-users" scenario).

Turns a hybridized :class:`~incubator_mxnet_tpu.gluon.HybridBlock`, a
bound ``Module``, a raw ``Symbol`` or the legacy C-predict payload into
a production predictor:

* :class:`DynamicBatcher` — thread-safe request queue; batches assemble
  under ``GRAFT_SERVE_MAX_BATCH`` / ``GRAFT_SERVE_MAX_WAIT_MS``, pad to
  power-of-two shape buckets (one compiled signature per (model, shape,
  bucket)) and dispatch as ONE device call, with a bit-parity probe
  against the unbatched forward (serving/batcher.py);
* :class:`ModelRegistry` — multi-model weight residency under
  ``GRAFT_SERVE_MEMORY_BYTES`` with LRU eviction and versioned hot-swap
  over ``KVStore.pull_many_async`` (serving/registry.py);
* SLO telemetry — per-request queue_wait/batch_assembly/device_compute/
  host_io decomposition with an exact-sum conservation contract,
  ``graft_serve_*`` metrics incl. rolling p50/p99 gauges, blackbox
  batch journals and watchdog-named stuck batches (serving/slo.py);
* ``python -m incubator_mxnet_tpu.serving --selftest`` — the lint-tier
  smoke; ``bench_serving.py`` — p50/p99 vs offered QPS plus
  batched-vs-serial throughput in BENCH JSON.

:class:`Server` bundles the three::

    srv = serving.Server(max_wait_ms=2)
    srv.load("mnist", block=net, example=example_x)
    fut = srv.submit("mnist", x)            # ServeFuture
    y = fut.get(timeout=1.0)
    srv.swap("mnist", new_params)           # hot-swap, no torn weights
    srv.close()
"""
from __future__ import annotations

from .batcher import (DynamicBatcher, ServeFuture, ServeError,
                      DeadlineExceededError, serve_max_batch,
                      serve_max_wait_ms, serve_deadline_ms, parity_mode)
from .registry import (ModelRegistry, ModelHandle, SwapTicket,
                       serve_memory_bytes, serve_batch_mode,
                       default_registry)
from . import loader
from . import slo

__all__ = ["Server", "DynamicBatcher", "ServeFuture", "ServeError",
           "DeadlineExceededError", "ModelRegistry", "ModelHandle",
           "SwapTicket", "loader", "slo", "serve_max_batch",
           "serve_max_wait_ms", "serve_deadline_ms", "serve_memory_bytes",
           "serve_batch_mode", "parity_mode", "default_registry"]


class Server(object):
    """Registry + batcher in one object — the serving runtime."""

    def __init__(self, memory_bytes=None, max_batch=None, max_wait_ms=None,
                 registry=None):
        self.registry = registry if registry is not None \
            else ModelRegistry(memory_bytes)
        self.batcher = DynamicBatcher(self.registry, max_batch=max_batch,
                                      max_wait_ms=max_wait_ms)

    # -- model lifecycle -----------------------------------------------------
    def load(self, name, block=None, example=None, module=None,
             symbol=None, params=None, symbol_json=None, param_bytes=None,
             input_shapes=None, input_names=None):
        """Register a model from whichever source is given: ``block`` (+
        ``example``), ``module``, ``symbol`` (+ ``params``), or
        ``symbol_json`` + ``param_bytes`` (+ ``input_shapes``)."""
        if block is not None:
            return self.registry.load_block(name, block, example)
        if module is not None:
            return self.registry.load_module(name, module)
        if symbol is not None:
            return self.registry.load_symbol(
                name, symbol, params, input_shapes=input_shapes,
                input_names=input_names)
        if symbol_json is not None:
            return self.registry.load_bytes(name, symbol_json, param_bytes,
                                            input_shapes)
        raise ValueError("pass one of block=, module=, symbol=, "
                         "symbol_json=")

    def swap(self, name, new_params):
        """Hot-swap ``name`` to a new weight version (streams in async,
        flips atomically; in-flight requests keep the old version)."""
        return self.registry.swap(name, new_params)

    def begin_swap(self, name, new_params):
        return self.registry.begin_swap(name, new_params)

    # -- serving -------------------------------------------------------------
    def submit(self, name, x, deadline_ms=None):
        """Enqueue one example; returns a :class:`ServeFuture`.
        ``deadline_ms`` (default GRAFT_SERVE_DEADLINE_MS) bounds queue
        time — an expired request is shed with
        :class:`~.batcher.DeadlineExceededError`."""
        return self.batcher.submit(name, x, deadline_ms=deadline_ms)

    def predict(self, name, x, timeout=30.0):
        """Synchronous convenience: submit + get."""
        return self.submit(name, x).get(timeout)

    def warmup(self, name, example, buckets=None):
        """Pre-compile the (shape, bucket) signatures for ``example`` so
        production dispatches never pay an XLA compile: one direct call
        per bucket (and its parity probe) off the hot path."""
        import numpy as np
        import jax.numpy as jnp
        from .batcher import normalize_example, request_signature
        xs = normalize_example(example)     # the submit() normalization,
        sig = request_signature(xs)         # so warmup compiles EXACTLY
        #                                     the production signatures
        entry, params, _version = self.registry.acquire(name)
        if buckets is None:
            buckets, b = [], 1
            while b < self.batcher._max_batch:
                buckets.append(b)
                b <<= 1
            buckets.append(self.batcher._max_batch)
        for b in sorted(set(buckets)):
            batched = [jnp.asarray(np.stack([v] * b)) for v in xs]
            out = entry.jit_for(b)(params, *batched)
            outs = out if isinstance(out, tuple) else (out,)
            self.batcher._maybe_probe(name, sig, b, entry, params,
                                      batched, outs)
        return buckets

    # -- lifecycle -----------------------------------------------------------
    def stats(self):
        return {
            "registry": self.registry.stats(),
            "queue_depth": self.batcher.queue_depth,
            "batches": self.batcher.batches_total,
            "requests": self.batcher.requests_total,
            "slo": slo.summary(),
        }

    def close(self):
        self.batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
