"""graftserve SLO telemetry — per-request latency decomposition in
graftlens style.

Every request's end-to-end wall time decomposes into FOUR components
that sum EXACTLY to the request wall (the same conservation contract
``telemetry/lens.py`` keeps per training step):

* ``queue_wait``      — enqueue → picked into a batch by the dispatcher,
* ``batch_assembly``  — pick → padded batch tensor built and on device,
* ``device_compute``  — dispatch → ``block_until_ready`` (ONE compiled
                        device call per batch; also booked on the
                        graftlens DEVICE ledger, so serving compute is
                        measured on the device, not just host wall),
* ``host_io``         — the residual: output rows sliced/converted and
                        the response delivered.

``host_io = wall - (queue_wait + batch_assembly + device_compute)``
makes the sum exact by construction (IEEE: ``s + (wall - s) == wall``);
the first three are direct timestamp diffs of the request timeline.

Requests land in a ring of the last ``GRAFT_SERVE_RING`` (default 1024)
records; every batch completion republishes rolling p50/p99 gauges over
the ring (``graft_serve_latency_seconds{quantile=...}``) next to the
counters/histograms in ``telemetry/metrics.py`` (``graft_serve_*``).
"""
from __future__ import annotations

import os
import threading
from collections import deque

from ..telemetry import metrics as _tmetrics

__all__ = ["COMPONENTS", "decompose", "record_request", "record_batch",
           "requests", "quantiles", "component_quantile", "summary",
           "reset", "ring_size"]

COMPONENTS = ("queue_wait", "batch_assembly", "device_compute", "host_io")

_DEFAULT_RING = 1024


def ring_size():
    try:
        n = int(os.environ.get("GRAFT_SERVE_RING", str(_DEFAULT_RING)))
    except ValueError:
        return _DEFAULT_RING
    return max(n, 16)


_lock = threading.Lock()
_ring = deque(maxlen=ring_size())


def decompose(t_enq, t_pick, t_built, t_computed, t_done):
    """The request timeline → ``(wall_s, components)`` with the exact-sum
    contract: components are non-negative timestamp diffs except
    ``host_io``, the residual that makes the four sum to ``wall_s``
    bit-exactly."""
    wall = t_done - t_enq
    comp = {
        "queue_wait": max(t_pick - t_enq, 0.0),
        "batch_assembly": max(t_built - t_pick, 0.0),
        "device_compute": max(t_computed - t_built, 0.0),
    }
    s = comp["queue_wait"] + comp["batch_assembly"] + comp["device_compute"]
    comp["host_io"] = wall - s      # residual: sum == wall by construction
    return wall, comp


def record_request(model, version, wall_s, components, batch_size,
                   bucket, ok=True):
    """One finished request: ring + metrics.  Returns the record."""
    rec = {"model": model, "version": version, "wall_s": wall_s,
           "components": components, "batch_size": batch_size,
           "bucket": bucket, "ok": ok}
    with _lock:
        _ring.append(rec)
    _tmetrics.serve_request(model, wall_s, components)
    return rec


def record_batch(model, size, bucket):
    """One dispatched batch: size histogram + padding counter, then the
    rolling quantile gauges are refreshed from the ring."""
    _tmetrics.serve_batch(model, size, bucket)
    p50, p99 = quantiles()
    if p50 is not None:
        _tmetrics.serve_quantiles(p50, p99)


def requests():
    """The ring, oldest first (copies)."""
    with _lock:
        return [dict(r, components=dict(r["components"])) for r in _ring]


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def quantiles(records=None):
    """(p50_s, p99_s) over the ring (or an explicit record list)."""
    if records is None:
        with _lock:
            walls = [r["wall_s"] for r in _ring if r["ok"]]
    else:
        walls = [r["wall_s"] for r in records if r["ok"]]
    walls.sort()
    return _quantile(walls, 0.50), _quantile(walls, 0.99)


def component_quantile(component, q=0.99, records=None):
    """Quantile of ONE latency component over the ring's ok requests —
    e.g. ``component_quantile("queue_wait", 0.99)`` is the signal the
    graftpulse serving knob steers on (telemetry/autotune.py).  None on
    an empty ring or unknown component."""
    if component not in COMPONENTS:
        return None
    if records is None:
        with _lock:
            vals = [r["components"][component] for r in _ring if r["ok"]]
    else:
        vals = [r["components"][component] for r in records if r["ok"]]
    vals.sort()
    return _quantile(vals, q)


def summary(records=None):
    """Aggregate view over the ring: count, mean/p50/p99 latency, mean
    per-component seconds, mean batch size."""
    recs = requests() if records is None else list(records)
    ok = [r for r in recs if r["ok"]]
    if not ok:
        return {"requests": len(recs), "ok": 0}
    p50, p99 = quantiles(ok)
    n = len(ok)
    return {
        "requests": len(recs),
        "ok": n,
        "mean_ms": round(sum(r["wall_s"] for r in ok) / n * 1e3, 3),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
        "components_ms": {
            c: round(sum(r["components"][c] for r in ok) / n * 1e3, 3)
            for c in COMPONENTS},
        "mean_batch_size": round(sum(r["batch_size"] for r in ok) / n, 2),
    }


def reset():
    """Drop the ring (tests/benches)."""
    global _ring
    with _lock:
        _ring = deque(maxlen=ring_size())
