"""graftserve model registry — multi-model weight residency with LRU
eviction and versioned hot-swap.

A :class:`ModelRegistry` owns N models.  Each model is ``(fn, params,
version)``: one pure jittable forward (serving/loader.py) compiled once
per shape-bucket signature by ``jax.jit``'s cache, plus the raw weight
arrays — the RESIDENCY UNIT.

* **Budget** — ``GRAFT_SERVE_MEMORY_BYTES`` (0/unset = unlimited; the
  constructor's ``memory_bytes`` overrides).  Loading or reloading past
  the budget evicts least-recently-USED models first (every dispatch
  marks use).  An evicted model keeps its loader closure; the next
  request reloads it transparently (``reload`` lifecycle tick).  The
  ``graft_serve_resident_*`` gauges sit next to the engine's
  ``graft_device_memory_bytes`` device gauges so residency and actual
  allocator pressure read side by side.

* **Hot-swap** — :meth:`begin_swap` streams a new weight version in via
  ``KVStore.pull_many_async`` (the graftduplex PR 9 wire: out arrays
  rebind through async XLA dispatches at issue, the open
  flight-recorder bracket names the in-flight swap bucket for the
  watchdog) while the OLD version keeps serving; :meth:`SwapTicket.commit`
  waits the handle and flips the model's ``(params, version)`` pair
  atomically under the registry lock.  A dispatch snapshots the pair
  under the same lock, so no request ever sees torn weights —
  every response is entirely old-version or entirely new-version.

Thread-safety: ONE registry lock; grafttsan registers the registry as
an EH202 region (entered inside the lock), so any future code path
touching registry state without the lock is named under ``GRAFT_TSAN=1``.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

import numpy as np

from ..analysis import tsan as _tsan
from ..telemetry import metrics as _tmetrics
from . import loader as _loader

__all__ = ["ModelRegistry", "ModelHandle", "SwapTicket",
           "serve_memory_bytes", "serve_batch_mode", "default_registry"]


def serve_memory_bytes():
    """GRAFT_SERVE_MEMORY_BYTES: registry residency budget in bytes
    (0 or unset = unlimited)."""
    try:
        return int(os.environ.get("GRAFT_SERVE_MEMORY_BYTES", "0"))
    except ValueError:
        return 0


def serve_batch_mode():
    """GRAFT_SERVE_BATCH_MODE: how a padded bucket becomes one device
    call.

    * ``exact`` (default) — the bucket program is B per-example
      subgraphs concatenated (each row IS the bucket-1 graph, so XLA's
      per-shape lowering reproduces the unbatched forward bit-for-bit;
      measured ~250x over per-row dispatch on the CPU bench — the win
      is dispatch amortization, which is what dominates serving small
      models);
    * ``fused`` — the bucket program runs over the (B,)+shape batch
      directly (true batched gemms, the maximum-kernel-efficiency mode
      for real accelerators).  XLA may legally pick batch-size-dependent
      kernels whose results differ by ULPs from the unbatched forward;
      the batcher's parity probe demotes any (model, shape) where that
      happens."""
    v = os.environ.get("GRAFT_SERVE_BATCH_MODE", "exact").strip().lower()
    return "fused" if v == "fused" else "exact"


def _nbytes(param_vals):
    total = 0
    for v in param_vals.values():
        n = 1
        for s in v.shape:
            n *= int(s)
        total += n * np.dtype(v.dtype).itemsize
    return total


def _exact_batched(fn, bucket):
    """The ``exact`` bucket program: ``bucket`` per-example subgraphs of
    ``fn`` concatenated along the batch axis — ONE device call whose
    row ``i`` is the bucket-1 graph of row ``i``, so the batched result
    reproduces the unbatched forward bit-for-bit by construction."""
    import jax.numpy as jnp

    def batched(params, *xbs):
        outs = [fn(params, *[xb[i:i + 1] for xb in xbs])
                for i in range(bucket)]
        if isinstance(outs[0], tuple):
            return tuple(jnp.concatenate([o[k] for o in outs], 0)
                         for k in range(len(outs[0])))
        return jnp.concatenate(outs, 0)

    return batched


class ModelHandle(object):
    """One registered model.  The handle stays valid across evictions
    (weights reload on next use) and hot-swaps (version bumps); it is
    what ``predict.Predictor`` keeps and what the batcher dispatches
    through."""

    __slots__ = ("name", "input_names", "_fn", "_jit", "_exact_jits",
                 "_params", "_version", "_resident", "_loader", "_nbytes",
                 "_registry", "_loading", "loaded_at", "parity_ok",
                 "no_batch", "__weakref__")

    def __init__(self, registry, name, fn, param_vals, input_names,
                 loader=None):
        import jax
        self._registry = registry
        self._loading = None        # per-entry reload latch (an Event
        #                             while one thread runs the loader
        #                             OUTSIDE the registry lock)
        self.name = name
        self.input_names = list(input_names)
        self._fn = fn
        self._jit = jax.jit(fn)
        self._exact_jits = {}       # bucket -> jitted exact-batch program
        self._params = dict(param_vals)
        self._version = 1
        self._resident = True
        self._loader = loader
        self._nbytes = _nbytes(self._params)
        self.loaded_at = time.time()
        # parity-probe verdicts live ON the handle: they are a property
        # of this handle's PROGRAM, so they survive hot-swaps (same fn)
        # but never leak to a different model re-registered under the
        # same name (fresh handle, fresh verdicts)
        self.parity_ok = set()      # (sig, bucket) probed clean (exact)
        self.no_batch = set()       # sig demoted to per-request dispatch

    def jit_for(self, bucket, mode=None):
        """The compiled dispatch entry for one batch bucket: the plain
        jit in ``fused`` mode (or bucket 1 — identical either way), the
        concat-of-subgraphs program in ``exact`` mode (see
        :func:`serve_batch_mode`)."""
        mode = serve_batch_mode() if mode is None else mode
        if bucket <= 1 or mode == "fused":
            return self._jit
        jit_fn = self._exact_jits.get(bucket)
        if jit_fn is None:
            import jax
            jit_fn = self._exact_jits.setdefault(
                bucket, jax.jit(_exact_batched(self._fn, bucket)))
        return jit_fn

    @property
    def version(self):
        return self._version

    @property
    def resident(self):
        return self._resident

    @property
    def nbytes(self):
        return self._nbytes

    def acquire(self):
        """Snapshot ``(handle, param_vals, version)`` for one dispatch —
        atomic under the registry lock (hot-swap flips the same pair
        there), marks LRU use, reloads if evicted."""
        return self._registry.acquire(self.name)

    def predict(self, *inputs):
        """Direct single dispatch (no batching): one compiled device
        call over ``inputs`` (raw arrays / NDArrays).  The legacy
        C-predict surface serves through this."""
        from ..ndarray import NDArray
        import jax.numpy as jnp
        vals = [v._read() if isinstance(v, NDArray) else jnp.asarray(v)
                for v in inputs]
        entry, params, _version = self.acquire()
        return entry._jit(params, *vals)


class SwapTicket(object):
    """An in-flight hot-swap: new weights streaming in via one
    ``pull_many_async`` handle while the old version serves.  ``commit``
    waits the stream and flips atomically; ``abandon`` drops it (the old
    version keeps serving)."""

    __slots__ = ("_registry", "name", "target_version", "_outs", "_handle",
                 "_done")

    def __init__(self, registry, name, target_version, outs, handle):
        self._registry = registry
        self.name = name
        self.target_version = target_version
        self._outs = outs           # name -> out NDArray (streaming in)
        self._handle = handle
        self._done = False

    @property
    def done(self):
        return self._done

    def commit(self):
        """Wait the in-flight pulls, then flip the model's (params,
        version) pair atomically.  Returns the new version — assigned
        at COMMIT time as a monotonic bump (``target_version`` is the
        projection from begin_swap time; overlapping swaps each get a
        distinct, increasing version, last commit wins the weights).
        A failed wait leaves the ticket live: ``abandon()`` (or a
        retry) still works — ``_done`` flips only on success."""
        if self._done:
            return self.target_version
        self._handle.wait()             # may raise: ticket stays live
        new_params = {n: o._read() for n, o in self._outs.items()}
        self.target_version = self._registry._commit_swap(self.name,
                                                          new_params)
        self._done = True
        return self.target_version

    def abandon(self):
        """Drop the swap without flipping (old version keeps serving)."""
        if self._done:
            return
        self._done = True
        self._handle.abandon()


class ModelRegistry(object):
    """name → :class:`ModelHandle` with LRU residency under a byte
    budget."""

    def __init__(self, memory_bytes=None):
        self._lock = threading.RLock()
        self._models = OrderedDict()        # name -> ModelHandle, LRU order
        self._budget = serve_memory_bytes() if memory_bytes is None \
            else int(memory_bytes)
        self.loads_total = 0
        self.reloads_total = 0
        self.evictions_total = 0
        self.swaps_total = 0

    # -- loading -------------------------------------------------------------
    @staticmethod
    def _snapshot_loader(params):
        """Reload closure over HOST copies of the load-time weights.
        Reading the LIVE source block/module on reload would silently
        fast-forward an evicted model to retrained weights under its
        unchanged version number — the inverse of the stale-resurrection
        hole ``_commit_swap`` closes.  An eviction must round-trip to
        the exact registered version; new weights arrive ONLY via the
        versioned swap path."""
        host = {n: np.asarray(v) for n, v in params.items()}

        def reload():
            import jax.numpy as jnp
            return {n: jnp.asarray(v) for n, v in host.items()}

        return reload

    def load_block(self, name, block, example, train=False):
        """Register a (preferably hybridized) HybridBlock.  The weight
        snapshot is taken NOW; training the block further does not
        change what this registry serves — publish new weights with
        :meth:`swap`."""
        fn, params, input_names = _loader.block_model(block, example,
                                                      train=train)
        return self._install(name, fn, params, input_names,
                             self._snapshot_loader(params))

    def load_module(self, name, module):
        """Register a bound, initialized Module (weights snapshotted at
        load, like :meth:`load_block` — swap to publish new ones)."""
        fn, params, input_names = _loader.module_model(module)
        return self._install(name, fn, params, input_names,
                             self._snapshot_loader(params))

    def load_symbol(self, name, symbol, params, input_shapes=None,
                    input_names=None):
        """Register a Symbol + explicit params."""
        fn, param_vals, input_names = _loader.symbol_model(
            symbol, params, input_shapes=input_shapes,
            input_names=input_names)
        snapshot = dict(param_vals)
        return self._install(name, fn, param_vals, input_names,
                             lambda: dict(snapshot))

    def load_bytes(self, name, symbol_json, param_bytes, input_shapes):
        """Register the legacy C-predict payload (symbol JSON + .params
        bytes, parsed in memory by ``nd.load_buffer``).  The BYTES are
        retained host-side as the reload source, so eviction frees the
        parsed device arrays while the model stays reloadable."""
        fn, param_vals, input_names = _loader.bytes_model(
            symbol_json, param_bytes, input_shapes)

        def reload():
            _fn, pv, _names = _loader.bytes_model(
                symbol_json, param_bytes, input_shapes)
            return pv

        return self._install(name, fn, param_vals, input_names, reload)

    def _install(self, name, fn, param_vals, input_names, loader):
        with self._lock, _tsan.region(self, "registry"):
            if name in self._models:
                raise ValueError("model %r already registered (use swap "
                                 "for a new weight version, or unload "
                                 "first)" % name)
            handle = ModelHandle(self, name, fn, param_vals, input_names,
                                 loader=loader)
            self._models[name] = handle
            self.loads_total += 1
            _tmetrics.serve_model_event("load")
            self._evict_to_fit(protect=name)
            self._publish_residency()
            return handle

    # -- use / residency -----------------------------------------------------
    def get(self, name):
        with self._lock:
            return self._models.get(name)

    def acquire(self, name):
        """(handle, param_vals, version) snapshot for one dispatch:
        atomic vs hot-swap, marks LRU use, transparently reloads an
        evicted model (evicting others to fit).  The handle picks the
        compiled entry per bucket (``jit_for``); params/version are the
        torn-weight-free pair.

        Reload runs OUTSIDE the registry lock (ROADMAP 11e): a cold
        model's loader — potentially seconds of parse + H2D — must not
        stall other models' dispatches.  A per-entry latch serializes
        concurrent reloads of the SAME model (one loader run, everyone
        else waits on the Event, never on the lock); the install step
        re-checks under the lock so a hot-swap or unload that raced the
        reload wins (its weights are newer than the reload source's)."""
        while True:
            with self._lock, _tsan.region(self, "registry"):
                entry = self._models.get(name)
                if entry is None:
                    raise KeyError("model %r is not registered" % name)
                if entry._resident:
                    self._models.move_to_end(name)
                    return entry, entry._params, entry._version
                if entry._loader is None:
                    raise RuntimeError("model %r was evicted and has no "
                                       "reload source" % name)
                latch = entry._loading
                if latch is None:
                    latch = entry._loading = threading.Event()
                    i_load = True
                else:
                    i_load = False
                loader = entry._loader
            if not i_load:
                # another thread is mid-reload: wait on ITS latch (not
                # the registry lock — other models keep dispatching),
                # then re-check from the top
                latch.wait()
                continue
            # the latch MUST open on every exit from here on — any
            # escaping exception (loader failure, a malformed params
            # mapping breaking _nbytes, a racing-unload KeyError) would
            # otherwise park every follower in latch.wait() forever
            try:
                params = dict(loader())         # the slow part: unlocked
                retry = False
                with self._lock, _tsan.region(self, "registry"):
                    entry._loading = None
                    current = self._models.get(name)
                    if current is entry and not entry._resident:
                        # a swap/unload that raced us wins: only install
                        # when the entry is still the one we loaded for
                        # AND still cold (commit_swap set fresher
                        # weights + resident)
                        entry._params = params
                        entry._nbytes = _nbytes(params)
                        entry._resident = True
                        self.reloads_total += 1
                        _tmetrics.serve_model_event("reload")
                        self._evict_to_fit(protect=name)
                        self._publish_residency()
                    if current is None:
                        raise KeyError("model %r was unloaded mid-reload"
                                       % name)
                    if current is not entry:
                        retry = True    # re-registered under the same
                        #                 name mid-reload: serve the NEW
                        #                 model (re-check from the top)
                    else:
                        self._models.move_to_end(name)
                        snap = (entry, entry._params, entry._version)
            except BaseException:
                with self._lock:
                    if entry._loading is latch:
                        # clear only OUR latch: a failure past the
                        # install step already cleared it, and a
                        # successor may have installed a new one —
                        # nulling that would let a third thread start a
                        # duplicate loader run
                        entry._loading = None
                raise
            finally:
                latch.set()
            if retry:
                continue
            return snap

    def unload(self, name):
        """Drop a model entirely (its handle goes stale)."""
        with self._lock, _tsan.region(self, "registry"):
            entry = self._models.pop(name, None)
            if entry is not None:
                entry._params = {}
                entry._resident = False
                _tmetrics.serve_model_event("unload")
                self._publish_residency()
            return entry is not None

    def evict(self, name):
        """Explicitly drop a model's weights (keeps the handle; next use
        reloads)."""
        with self._lock, _tsan.region(self, "registry"):
            entry = self._models.get(name)
            if entry is None or not entry._resident:
                return False
            self._evict_entry(entry)
            self._publish_residency()
            return True

    def _evict_entry(self, entry):
        entry._params = {}
        entry._resident = False
        self.evictions_total += 1
        _tmetrics.serve_model_event("evict")

    def _evict_to_fit(self, protect=None):
        """LRU-evict resident models until the budget holds.  The
        ``protect``-ed (just-loaded/just-used) model is never evicted —
        a single model bigger than the budget stays resident (it could
        never serve otherwise); the gauges make the overshoot visible."""
        if self._budget <= 0:
            return
        while self.resident_bytes() > self._budget:
            victim = None
            for entry in self._models.values():     # OrderedDict = LRU order
                if entry._resident and entry.name != protect:
                    victim = entry
                    break
            if victim is None:
                return
            self._evict_entry(victim)

    def resident_bytes(self):
        return sum(e._nbytes for e in self._models.values() if e._resident)

    def _publish_residency(self):
        _tmetrics.serve_residency(
            self.resident_bytes(),
            sum(1 for e in self._models.values() if e._resident),
            self._budget)

    # -- hot-swap ------------------------------------------------------------
    def begin_swap(self, name, new_params):
        """Start streaming a new weight version in: one local KVStore is
        seeded with ``new_params`` and pulled via ``pull_many_async`` —
        the async out-array writes stream while the CURRENT version
        keeps serving.  Returns a :class:`SwapTicket`; nothing changes
        until ``commit()``."""
        from .. import kvstore as _kvstore
        from ..ndarray import NDArray, zeros
        import jax.numpy as jnp
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError("model %r is not registered" % name)
            target_version = entry._version + 1
        kv = _kvstore.KVStore("local")
        keys, outs_list, outs = [], [], {}
        for pname in sorted(new_params):
            v = new_params[pname]
            v = v if isinstance(v, NDArray) else NDArray(jnp.asarray(v))
            key = pname
            kv.init(key, v)
            out = zeros(v.shape, dtype=np.dtype(v.dtype).name)
            keys.append(key)
            outs_list.append([out])
            outs[pname] = out
        handle = kv.pull_many_async(
            keys, outs_list,
            label="swap[%s v%d:%dp]" % (name, target_version, len(keys)))
        return SwapTicket(self, name, target_version, outs, handle)

    def swap(self, name, new_params):
        """begin_swap + commit in one call.  Returns the new version."""
        return self.begin_swap(name, new_params).commit()

    def _commit_swap(self, name, new_params):
        # the reload source must flip WITH the weights: any prior loader
        # (original bytes, the source block's params) would resurrect
        # pre-swap weights under the post-swap version after an
        # eviction.  Host np copies keep the device arrays evictable.
        host = {n: np.asarray(v) for n, v in new_params.items()}

        def reload():
            import jax.numpy as jnp
            return {n: jnp.asarray(v) for n, v in host.items()}

        with self._lock, _tsan.region(self, "registry"):
            entry = self._models.get(name)
            if entry is None:
                raise KeyError("model %r was unloaded mid-swap" % name)
            entry._params = new_params
            entry._nbytes = _nbytes(new_params)
            # monotonic bump at commit time: two overlapping swaps can
            # never share or regress a version number
            target_version = entry._version + 1
            entry._version = target_version
            entry._resident = True
            entry._loader = reload
            self.swaps_total += 1
            _tmetrics.serve_model_event("swap")
            self._evict_to_fit(protect=name)
            self._publish_residency()
            return target_version

    # -- introspection -------------------------------------------------------
    def models(self):
        with self._lock:
            return list(self._models.keys())

    def stats(self):
        with self._lock:
            return {
                "models": {
                    n: {"version": e._version, "resident": e._resident,
                        "nbytes": e._nbytes}
                    for n, e in self._models.items()},
                "resident_bytes": self.resident_bytes(),
                "budget_bytes": self._budget,
                "loads": self.loads_total,
                "reloads": self.reloads_total,
                "evictions": self.evictions_total,
                "swaps": self.swaps_total,
            }


_default = [None]
_default_lock = threading.Lock()


def default_registry():
    """The process-wide registry the legacy ``predict.Predictor``
    surface registers into (one loader, shared residency accounting)."""
    with _default_lock:
        if _default[0] is None:
            _default[0] = ModelRegistry()
        return _default[0]
