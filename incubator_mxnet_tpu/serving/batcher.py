"""graftserve dynamic batcher — request queue → padded shape-bucket →
ONE device call.

Requests (one example each) enqueue into per-``(model, input
signature)`` queues; a dispatcher thread assembles batches under two
knobs — ``GRAFT_SERVE_MAX_BATCH`` (dispatch when a queue holds that
many) and ``GRAFT_SERVE_MAX_WAIT_MS`` (dispatch whatever is there once
the OLDEST request has waited that long) — pads the batch to a
power-of-two bucket and dispatches the whole bucket as ONE compiled
call (the registry's per-model ``jax.jit``; XLA's compile cache keys on
the padded signature, so the signature set stays small: one entry per
(model, example shape, bucket), the ``CachedOp`` discipline).

**Bit-parity contract** (the PR 4 fused-step oracle discipline):

* within a signature it is STRUCTURAL — row ``i`` of the compiled
  program depends only on input row ``i`` (inference graphs have no
  cross-row ops), so co-batched requests and padding rows can never
  perturb a result;
* across signatures (a bucket-8 program vs the bucket-1 program) XLA
  may legally pick different kernels, so ``GRAFT_SERVE_PARITY=probe``
  (default) bit-compares row 0 of each NEW signature's first dispatch
  against the bucket-1 forward of the same request; a mismatch demotes
  that (model, shape) to per-request dispatch — the serving mirror of
  graftfuse's "degrade to the bit-identical path, never to wrong
  values" rail (``graft_serve_parity_fallbacks_total``).

Every dispatch runs inside a ``serve_batch`` flight-recorder bracket
naming (batch id, model, version, size, bucket) — a stuck batch is
tripped BY NAME by the graftwatch watchdog and shows as the in-flight
batch in crash dumps — and lands a ``serve_batch`` journal event with
the batch's latency split.  Device time of the dispatch is booked on
the graftlens device ledger.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..analysis import tsan as _tsan
from ..telemetry import blackbox as _blackbox
from ..telemetry import lens as _lens
from ..telemetry import metrics as _tmetrics
from . import slo as _slo

__all__ = ["DynamicBatcher", "ServeFuture", "ServeError",
           "DeadlineExceededError", "serve_max_batch", "serve_max_wait_ms",
           "serve_deadline_ms", "parity_mode"]

DEFAULT_MAX_BATCH = 32
DEFAULT_MAX_WAIT_MS = 5.0


def serve_deadline_ms():
    """GRAFT_SERVE_DEADLINE_MS: default per-request deadline (0/unset =
    none).  A request still queued when its deadline passes is SHED —
    failed with :class:`DeadlineExceededError` instead of dispatched —
    so an overloaded server spends device time only on work whose answer
    somebody still wants (graftarmor load-shedding)."""
    try:
        v = float(os.environ.get("GRAFT_SERVE_DEADLINE_MS", "0"))
    except ValueError:
        return None
    return v if v > 0 else None


def serve_max_batch():
    """GRAFT_SERVE_MAX_BATCH: dispatch a queue the moment it holds this
    many requests (default 32)."""
    try:
        n = int(os.environ.get("GRAFT_SERVE_MAX_BATCH",
                               str(DEFAULT_MAX_BATCH)))
    except ValueError:
        return DEFAULT_MAX_BATCH
    return max(n, 1)


def serve_max_wait_ms():
    """GRAFT_SERVE_MAX_WAIT_MS: dispatch whatever a queue holds once its
    oldest request has waited this long (default 5ms).  0 = dispatch
    immediately (batching only what piled up while the dispatcher was
    busy)."""
    try:
        v = float(os.environ.get("GRAFT_SERVE_MAX_WAIT_MS",
                                 str(DEFAULT_MAX_WAIT_MS)))
    except ValueError:
        return DEFAULT_MAX_WAIT_MS
    return max(v, 0.0)


def parity_mode():
    """GRAFT_SERVE_PARITY: ``probe`` (default) bit-checks each new batch
    signature against the bucket-1 forward and demotes mismatching
    (model, shape)s to per-request dispatch; ``off`` trusts XLA."""
    v = os.environ.get("GRAFT_SERVE_PARITY", "probe").strip().lower()
    return "off" if v in ("0", "off", "false", "no") else "probe"


class ServeError(RuntimeError):
    """A request failed (model error, shutdown, dispatch exception)."""


class DeadlineExceededError(ServeError):
    """The request's ``deadline_ms`` passed while it was still queued —
    it was shed, never dispatched.  Typed so callers can tell an
    overload rejection from a model failure and retry elsewhere."""

    def __init__(self, model, waited_ms):
        super().__init__(
            "request for model %r shed after %.1fms in queue "
            "(deadline exceeded)" % (model, waited_ms))
        self.model = model
        self.waited_ms = waited_ms


def normalize_example(x):
    """One request input → tuple of np arrays (the form requests queue
    as and signatures key on).  Shared by ``DynamicBatcher.submit`` and
    ``Server.warmup`` so warmup pre-compiles EXACTLY the signatures
    production dispatches hit."""
    from ..ndarray import NDArray
    xs = x if isinstance(x, (tuple, list)) else (x,)
    return tuple(np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
                 for v in xs)


def request_signature(xs):
    """The (shape, dtype) signature tuple of a normalized input."""
    return tuple((v.shape, str(v.dtype)) for v in xs)


class ServeFuture(object):
    """Handed back by :meth:`DynamicBatcher.submit`; resolves when the
    request's batch lands.  ``record`` carries the request's SLO
    decomposition after resolution."""

    __slots__ = ("_event", "_value", "_error", "record")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self.record = None

    def done(self):
        return self._event.is_set()

    def get(self, timeout=None):
        """Block until the response is ready; returns the output row
        (np.ndarray, or a tuple for multi-output models)."""
        if not self._event.wait(timeout):
            raise TimeoutError("serving request still in flight")
        if self._error is not None:
            raise self._error
        return self._value

    def _resolve(self, value, record):
        self._value = value
        self.record = record
        self._event.set()

    def _fail(self, exc):
        self._error = exc if isinstance(exc, Exception) \
            else ServeError(str(exc))
        self._event.set()


class _Request(object):
    __slots__ = ("model", "xs", "future", "t_enq", "t_pick", "t_built",
                 "t_computed", "t_deadline")

    def __init__(self, model, xs, deadline_ms=None):
        self.model = model
        self.xs = xs                # tuple of per-input np arrays
        self.future = ServeFuture()
        self.t_enq = time.perf_counter()
        self.t_pick = self.t_built = self.t_computed = None
        if deadline_ms is None:
            deadline_ms = serve_deadline_ms()
        self.t_deadline = None if deadline_ms is None \
            else self.t_enq + float(deadline_ms) / 1e3


def _bucket_for(n, max_batch):
    """Smallest power-of-two ≥ n, capped at max_batch — the compiled
    batch-signature set stays O(log max_batch) per shape."""
    b = 1
    while b < n and b < max_batch:
        b <<= 1
    return min(b, max_batch)


class DynamicBatcher(object):
    """The request queue + dispatcher thread.  One instance serves every
    model of its :class:`~incubator_mxnet_tpu.serving.ModelRegistry`.

    Thread-safety: one condition variable guards the queues; grafttsan
    registers the batcher as an EH202 region (entered inside the lock)
    so an unlocked touch of queue state is named under ``GRAFT_TSAN=1``.
    The dispatcher is a daemon thread with an explicit shutdown path
    (:meth:`close` — drains the queues, then joins)."""

    def __init__(self, registry, max_batch=None, max_wait_ms=None):
        self._registry = registry
        self._max_batch = serve_max_batch() if max_batch is None \
            else max(int(max_batch), 1)
        wait_ms = serve_max_wait_ms() if max_wait_ms is None \
            else max(float(max_wait_ms), 0.0)
        self._max_wait = wait_ms / 1e3
        self._wait_ms_base = wait_ms    # the configured value the
        #                                 autotuner relaxes back toward
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queues = OrderedDict()    # key -> deque[_Request]
        self._depth = 0
        self._flush_upto = -1.0     # requests enqueued at/before this
        #                             mark dispatch without max-wait
        self._closed = False
        self._thread = None
        self._batch_seq = itertools.count(1)
        self.batches_total = 0
        self.requests_total = 0
        # graftpulse: the batcher's max-batch / max-wait become live
        # autotuner targets (weak registration; ~free when GRAFT_AUTOTUNE
        # is off — the controller's observer returns immediately)
        try:
            from ..telemetry import autotune as _autotune
            _autotune.register_batcher(self)
        except Exception:
            pass

    # -- graftpulse live knobs ----------------------------------------------
    def max_batch(self):
        return self._max_batch

    def set_max_batch(self, n):
        """Live resize: takes effect on the next pick — bucket padding
        follows automatically (``_bucket_for`` caps at the new max, so
        a grown batch compiles at most one new bucket size)."""
        with self._cv:
            self._max_batch = max(int(n), 1)
            self._cv.notify()

    def max_wait_ms(self):
        return self._max_wait * 1e3

    def configured_max_wait_ms(self):
        """The construction-time max-wait — the ceiling the autotuner
        relaxes a squeezed wait back toward."""
        return self._wait_ms_base

    def set_max_wait_ms(self, ms):
        with self._cv:
            self._max_wait = max(float(ms), 0.0) / 1e3
            self._cv.notify()

    # -- submission ----------------------------------------------------------
    def submit(self, model, x, deadline_ms=None):
        """Enqueue ONE example for ``model``; returns a
        :class:`ServeFuture`.  ``x`` is a single input (np/NDArray/jax
        array) or a tuple for multi-input models; the model's forward
        sees it stacked under a leading batch axis.  ``deadline_ms``
        (default GRAFT_SERVE_DEADLINE_MS) bounds queue time: a request
        still undispatched when it expires is shed with
        :class:`DeadlineExceededError` and counted in
        ``graft_serve_shed_total``."""
        xs = normalize_example(x)
        req = _Request(model, xs, deadline_ms=deadline_ms)
        key = (model, request_signature(xs))
        with self._cv:
            if self._closed:
                raise ServeError("batcher is closed")
            with _tsan.region(self, "batcher"):
                self._queues.setdefault(key, deque()).append(req)
                self._depth += 1
                self.requests_total += 1
            _tmetrics.serve_queue_depth(self._depth)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="graftserve-batcher",
                    daemon=True)
                self._thread.start()
            self._cv.notify()
        return req.future

    def flush(self):
        """Make everything queued RIGHT NOW dispatchable immediately
        (ignore max-wait for the current contents only — requests
        arriving after the call keep the normal batching window, so a
        flush under sustained traffic cannot degrade later batching)."""
        with self._cv:
            self._flush_upto = time.perf_counter()
            self._cv.notify()

    # -- the dispatcher loop -------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                batch = None
                while not self._closed:
                    now = time.perf_counter()
                    batch, deadline = self._pick_locked(now)
                    if batch is not None:
                        break
                    timeout = None if deadline is None \
                        else max(deadline - now, 0.0)
                    self._cv.wait(timeout)
                if batch is None and self._closed:
                    # drain whatever is left, then exit
                    batch, _ = self._pick_locked(time.perf_counter(),
                                                 drain=True)
                    if batch is None:
                        return
            try:
                self._dispatch(batch)
            except Exception as exc:    # belt-and-braces: the dispatcher
                # thread must survive ANY dispatch bug — fail the batch's
                # futures instead of dying with them unresolved (a dead
                # loop would hang every later submit forever)
                for r in batch:
                    if not r.future.done():
                        r.future._fail(exc)
                import logging
                logging.getLogger("graftserve").exception(
                    "dispatch failed outside the batch error path")

    def _shed_locked(self, now):
        """graftarmor load-shedding: fail every queued request whose
        deadline passed (typed :class:`DeadlineExceededError`, counted
        in ``graft_serve_shed_total``) — it was never dispatched, so no
        device time is burned on an answer nobody is waiting for.
        Returns the earliest LIVE deadline so the dispatcher's wait
        wakes in time to shed the next expiry."""
        earliest = None
        shed = []
        for key in list(self._queues):
            q = self._queues[key]
            keep = deque()
            for r in q:
                if r.t_deadline is not None and now >= r.t_deadline:
                    shed.append(r)
                else:
                    keep.append(r)
                    if r.t_deadline is not None:
                        earliest = r.t_deadline if earliest is None \
                            else min(earliest, r.t_deadline)
            if len(keep) != len(q):
                if keep:
                    self._queues[key] = keep
                else:
                    del self._queues[key]
        if shed:
            self._depth -= len(shed)
            for r in shed:
                waited = (now - r.t_enq) * 1e3
                r.future._fail(DeadlineExceededError(r.model, waited))
                _tmetrics.serve_shed(r.model)
                _blackbox.record("serve_shed", model=r.model,
                                 waited_ms=round(waited, 3))
        return earliest

    def _pick_locked(self, now, drain=False):
        """Choose the ripest ready queue (full, expired, flushed or
        draining); returns (requests, next_deadline)."""
        with _tsan.region(self, "batcher"):
            shed_wake = self._shed_locked(now)
            best_key = None
            best_enq = None
            deadline = shed_wake
            for key, q in self._queues.items():
                if not q:
                    continue
                head = q[0].t_enq
                ready = (len(q) >= self._max_batch or drain
                         or head <= self._flush_upto
                         or now - head >= self._max_wait)
                if ready:
                    if best_enq is None or head < best_enq:
                        best_key, best_enq = key, head
                else:
                    d = head + self._max_wait
                    deadline = d if deadline is None else min(deadline, d)
            if best_key is None:
                return None, deadline
            q = self._queues[best_key]
            batch = [q.popleft() for _ in range(min(len(q),
                                                    self._max_batch))]
            if not q:
                del self._queues[best_key]
            self._depth -= len(batch)
        _tmetrics.serve_queue_depth(self._depth)
        t_pick = time.perf_counter()
        for r in batch:
            r.t_pick = t_pick
        return batch, None

    # -- dispatch ------------------------------------------------------------
    def _dispatch(self, reqs):
        model = reqs[0].model
        bid = next(self._batch_seq)
        try:
            # graftarmor chaos site: a serving dispatch can be failed or
            # delayed by GRAFT_FAULTS without touching the model
            from ..armor import faults as _faults
            _faults.fault_point("serve.dispatch", model=model,
                                size=len(reqs))
            entry, params, version = self._registry.acquire(model)
        except Exception as exc:
            self._fail_batch(reqs, exc, model, bid)
            return
        sig = request_signature(reqs[0].xs)
        if sig in entry.no_batch and len(reqs) > 1:
            # parity-demoted signature: per-request dispatch, still one
            # compiled call each — bit-identical to the unbatched path
            for r in reqs:
                self._run_batch([r], entry, params, version, bid,
                                demoted=True)
                bid = next(self._batch_seq)
            return
        self._run_batch(reqs, entry, params, version, bid)

    def _run_batch(self, reqs, entry, params, version, bid, demoted=False):
        import jax
        import jax.numpy as jnp
        model = reqs[0].model
        n = len(reqs)
        bucket = _bucket_for(n, self._max_batch)
        sig = request_signature(reqs[0].xs)
        try:
            jit_fn = entry.jit_for(bucket)
            # assembly: stack + pad to the bucket, then H2D
            n_inputs = len(reqs[0].xs)
            xvals = []
            for i in range(n_inputs):
                shape, dtype = reqs[0].xs[i].shape, reqs[0].xs[i].dtype
                buf = np.zeros((bucket,) + shape, dtype)
                for j, r in enumerate(reqs):
                    buf[j] = r.xs[i]
                xvals.append(jnp.asarray(buf))
            t_built = time.perf_counter()
            for r in reqs:
                r.t_built = t_built
            with _blackbox.in_flight("serve_batch", {
                    "batch": bid, "model": model, "version": version,
                    "size": n, "bucket": bucket, "demoted": demoted}):
                out = jit_fn(params, *xvals)
                outs = out if isinstance(out, tuple) else (out,)
                jax.block_until_ready(outs)
            t_computed = time.perf_counter()
            for r in reqs:
                r.t_computed = t_computed
            _lens.device(t_built, t_computed)   # the device-ledger view
            if self._maybe_probe(model, sig, bucket, entry, params,
                                 xvals, outs):
                # probe mismatch: discard the batched result and re-run
                # THIS batch per-request too — a demoted signature never
                # delivers a non-parity row, not even its first batch
                for r in reqs:
                    self._run_batch([r], entry, params, version,
                                    next(self._batch_seq), demoted=True)
                return
            # host_io: rows out of the device result, futures resolved
            host_outs = [np.asarray(o) for o in outs]
            single = not isinstance(out, tuple)
            for j, r in enumerate(reqs):
                row = tuple(o[j] for o in host_outs)
                value = row[0] if single else row
                t_done = time.perf_counter()
                wall, comp = _slo.decompose(r.t_enq, r.t_pick, r.t_built,
                                            r.t_computed, t_done)
                rec = _slo.record_request(model, version, wall, comp,
                                          batch_size=n, bucket=bucket)
                r.future._resolve(value, rec)
            self.batches_total += 1
            _slo.record_batch(model, n, bucket)
            if _lens.enabled():
                # one lens window per batch cycle on the dispatcher
                # thread: the device ledger (booked above) lands in a
                # ring record with origin "serve_batch", so serving's
                # device_compute is visible in the SAME per-step
                # attribution stream training uses
                _lens.step_end("serve_batch",
                               extra={"batch_size": n, "model": model})
            _blackbox.record(
                "serve_batch", batch=bid, model=model, version=version,
                size=n, bucket=bucket, demoted=demoted,
                compute_ms=round((t_computed - t_built) * 1e3, 3),
                queue_wait_ms=round(
                    (reqs[0].t_pick - reqs[0].t_enq) * 1e3, 3))
        except Exception as exc:
            self._fail_batch(reqs, exc, model, bid)

    def _maybe_probe(self, model, sig, bucket, entry, params, xvals,
                     outs):
        """``GRAFT_SERVE_PARITY=probe``: row 0 of the batched dispatch
        must be bit-equal to the bucket-1 forward of the same request.
        In ``exact`` batch mode the clean verdict is cached per (sig,
        bucket) — parity there is structural, one probe per signature
        proves the wiring.  In ``fused`` mode kernel divergence is
        VALUE-dependent, so every dispatch is spot-checked (row 0; full
        per-row checking would be the unbatched path itself).  Verdicts
        live on the handle: they survive hot-swaps (same program) and
        die with re-registration.  Returns True when the dispatch
        mismatched and the signature was demoted to per-request
        dispatch."""
        if bucket <= 1 or parity_mode() == "off":
            return False
        from .registry import serve_batch_mode
        cacheable = serve_batch_mode() == "exact"
        if (cacheable and (sig, bucket) in entry.parity_ok) \
                or sig in entry.no_batch:
            return False
        ref = entry.jit_for(1)(params, *[v[:1] for v in xvals])
        refs = ref if isinstance(ref, tuple) else (ref,)
        for r, o in zip(refs, outs):
            if np.asarray(r)[0].tobytes() != np.asarray(o)[0].tobytes():
                entry.no_batch.add(sig)
                _tmetrics.serve_parity_fallback(model)
                _blackbox.record("serve_parity_fallback", model=model,
                                 bucket=bucket)
                import logging
                logging.getLogger("graftserve").warning(
                    "parity probe: batched output of model %r (bucket %d) "
                    "differs from the unbatched forward — demoting this "
                    "shape to per-request dispatch", model, bucket)
                return True
        if cacheable:
            entry.parity_ok.add((sig, bucket))
        return False

    def _fail_batch(self, reqs, exc, model, bid):
        _tmetrics.serve_errors(model, len(reqs))
        _blackbox.record("serve_batch", batch=bid, model=model,
                         size=len(reqs), error=repr(exc))
        for r in reqs:
            r.future._fail(exc)

    # -- lifecycle -----------------------------------------------------------
    @property
    def queue_depth(self):
        return self._depth

    def close(self):
        """Shut the dispatcher down: queued requests are drained
        (dispatched), then the thread joins.  Idempotent."""
        with self._cv:
            if self._closed:
                thread = None
            else:
                self._closed = True
                thread = self._thread
            self._cv.notify_all()
        if thread is not None:
            thread.join(timeout=30.0)
        # no thread ever started (or it exited early): drain inline
        while True:
            with self._cv:
                batch, _ = self._pick_locked(time.perf_counter(),
                                             drain=True)
            if batch is None:
                break
            self._dispatch(batch)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
