"""graftserve CLI.

    python -m incubator_mxnet_tpu.serving --selftest
        Lint smoke tier: a hybridized MLP serves threaded traffic
        through the dynamic batcher (bit-parity vs the eager forward
        asserted per request), the per-request SLO decomposition must
        conserve exactly, a mid-traffic hot-swap must flip atomically
        (every response entirely old- or new-version), and a tight
        residency budget must LRU-evict and transparently reload.
        Exit 1 on any regression.

    python -m incubator_mxnet_tpu.serving --demo [--json]
        Small human-readable demo: serve a few hundred requests and
        print the SLO summary + registry stats.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading

import numpy as np


def _build_net(seed=0, din=16, dh=32, dout=8, scale=1.0):
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    class MLP(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d1 = gluon.nn.Dense(dh, activation="relu")
                self.d2 = gluon.nn.Dense(dout)

        def hybrid_forward(self, F, x):
            return F.tanh(self.d2(self.d1(x)))

    net = MLP()
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    rs = np.random.RandomState(seed)
    net(mx.nd.array(rs.randn(1, din).astype(np.float32)))  # shapes
    for _name, p in net.collect_params().items():
        p.data()._write(jnp.asarray(
            (rs.randn(*p.shape) * 0.5 * scale).astype(np.float32)))
    return net


def selftest():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import serving
    from incubator_mxnet_tpu.telemetry import blackbox

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)
            print("graftserve selftest FAIL: %s" % msg, file=sys.stderr)

    din = 16
    net = _build_net(din=din)
    rs = np.random.RandomState(7)
    example = rs.randn(din).astype(np.float32)

    with serving.Server(max_batch=8, max_wait_ms=2) as srv:
        srv.load("mlp", block=net, example=mx.nd.array(example[None]))
        srv.warmup("mlp", example)      # the per-request example shape

        # threaded traffic: batched responses must be bit-equal to the
        # eager (unbatched) forward.  Requests are single examples of
        # shape (din,); the batcher stacks them under the batch axis.
        xs = [rs.randn(din).astype(np.float32) for _ in range(24)]
        futs = [None] * len(xs)

        def client(lo, hi):
            for i in range(lo, hi):
                futs[i] = srv.submit("mlp", xs[i])

        threads = [threading.Thread(target=client, args=(k * 8, k * 8 + 8))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        outs = [f.get(timeout=30.0) for f in futs]
        for i, (x, y) in enumerate(zip(xs, outs)):
            ref = net(mx.nd.array(x[None])).asnumpy()[0]
            if y.tobytes() != ref.tobytes():
                check(False, "request %d: batched != unbatched forward" % i)
                break
        else:
            print("parity: %d threaded requests bit-equal to the eager "
                  "forward" % len(outs))
        check(not srv.registry.get("mlp").no_batch,
              "the parity probe demoted a signature on the reference MLP")

        # SLO conservation: the four components sum EXACTLY to wall
        for f in futs:
            r = f.record
            s = sum(r["components"][c] for c in serving.slo.COMPONENTS)
            check(s == r["wall_s"],
                  "decomposition not conserved: %r != %r" % (s, r["wall_s"]))
        print("conservation: queue_wait+batch_assembly+device_compute+"
              "host_io == wall for all %d requests" % len(futs))

        # hot-swap mid-traffic: every response entirely old or new
        _fn, pv = net.serving_fn(mx.nd.array(example[None]))
        new_params = {n: np.asarray(v) * 2.0 for n, v in pv.items()}
        ticket = srv.begin_swap("mlp", new_params)
        pre = srv.predict("mlp", xs[0])     # old version still serving
        v2 = ticket.commit()
        post = srv.predict("mlp", xs[0])
        check(v2 == 2, "swap did not bump the version (got %r)" % v2)
        check(pre.tobytes() == outs[0].tobytes(),
              "pre-commit response changed under an in-flight swap")
        check(post.tobytes() != outs[0].tobytes(),
              "post-commit response still serves old weights")

        # batches actually batched + journaled
        evts = [e["data"] for e in blackbox.events()
                if e["kind"] == "serve_batch"]
        check(len(evts) >= 1, "no serve_batch journal events")
        check(any(e.get("size", 0) > 1 for e in evts),
              "no batch assembled more than one request")

    # LRU eviction under a tight budget: two models fit, the third
    # evicts the least-recently-used; a request to the evicted model
    # transparently reloads it
    h = serving.ModelRegistry(memory_bytes=1)      # nothing fits next to
    nets = [_build_net(seed=s) for s in (1, 2)]    # each other
    ha = h.load_block("a", nets[0], mx.nd.array(example[None]))
    hb = h.load_block("b", nets[1], mx.nd.array(example[None]))
    check(not ha.resident and hb.resident,
          "budget=1: expected only the newest model resident "
          "(a=%s b=%s)" % (ha.resident, hb.resident))
    h.acquire("a")                                  # reload a, evict b
    check(ha.resident and not hb.resident,
          "acquire did not reload the evicted model / evict the LRU one")
    check(h.reloads_total >= 1 and h.evictions_total >= 2,
          "eviction/reload counters did not move: %r" % (h.stats(),))
    print("residency: LRU eviction + transparent reload under a tight "
          "budget OK (evictions=%d reloads=%d)"
          % (h.evictions_total, h.reloads_total))

    if failures:
        print("graftserve selftest: %d failure(s)" % len(failures),
              file=sys.stderr)
        return 1
    print("graftserve selftest OK (batched parity, SLO conservation, "
          "atomic hot-swap, LRU residency)")
    return 0


def demo(as_json=False):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import serving

    net = _build_net()
    rs = np.random.RandomState(3)
    with serving.Server(max_batch=16, max_wait_ms=1) as srv:
        srv.load("mlp", block=net, example=mx.nd.array(
            rs.randn(1, 16).astype(np.float32)))
        futs = [srv.submit("mlp", rs.randn(1, 16).astype(np.float32))
                for _ in range(256)]
        for f in futs:
            f.get(timeout=30.0)
        stats = srv.stats()
    if as_json:
        print(json.dumps(stats, default=str))
    else:
        s = stats["slo"]
        print("graftserve demo: %d requests, %d batches "
              "(mean batch %.1f)" % (stats["requests"], stats["batches"],
                                     s.get("mean_batch_size", 0)))
        print("  latency p50 %.3fms p99 %.3fms | components (mean ms): %s"
              % (s.get("p50_ms", 0), s.get("p99_ms", 0),
                 s.get("components_ms")))
        print("  registry: %s" % stats["registry"])
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m incubator_mxnet_tpu.serving")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--demo", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.demo:
        return demo(as_json=args.json)
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
