"""graftduplex — the full-duplex step schedulers, shared by
``gluon.Trainer`` and ``module.Module``.

Two sides of one wire:

* :class:`BucketScheduler` (the push/reduce side, graftlap PR 7): armed
  with a bucket plan, it hangs grad-ready hooks on the host's gradient
  carriers; the moment the last (param, context) gradient of a bucket
  finalizes MID-BACKWARD, the bucket's concatenated flat buffer is built
  with the host's own packing math and shipped through
  ``KVStore.reduce_many_async`` while backward keeps producing
  earlier-layer gradients.  PR 9 generalizes it behind a small host
  protocol (``_sched_*`` methods) so ``Module``'s executor grad arrays
  ride the same machinery ``gluon.Trainer`` got.

* :class:`PullScheduler` (the pull/broadcast side, new): after the
  store-side update, each bucket's weight pull is issued as a
  ``KVStore.pull_many_async`` handle and FIRST-TOUCH hooks are installed
  on the out arrays — the next forward's first read of any covered
  weight waits that bucket's handle (``NDArray._touch_hook``, checked at
  the top of ``_read``), so updated weights stream back under data
  loading and the early layers.  Version stamps taken at issue gate the
  apply: an array the user overwrote between steps keeps the user's
  bytes (the serial pull-then-write ordering) and flags the round stale,
  which the consumer answers by falling back to the serial pull for the
  next round — exactly mirroring the reduce side's stale-grad fallback.

Both schedulers degrade to the bit-identical serial paths, never to
wrong values.  Env switches: ``GRAFT_OVERLAP`` (reduce side),
``GRAFT_OVERLAP_PULL`` (pull side), ``GRAFT_BUCKET_ORDER`` (tape|index
bucket packing — see ``gluon.Trainer._plan_order``).
"""
from __future__ import annotations

import os
import time
import weakref

import numpy as np

from . import engine as _engine
from .analysis import tsan as _tsan

__all__ = ["Bucket", "BucketScheduler", "PullScheduler", "bucket_order",
           "overlap_pull_enabled", "plan_pull_groups", "concat_ctx_sum",
           "publish_pull_round", "serial_pull", "pull_round"]

DEFAULT_BUCKET_BYTES = 4 << 20      # 4 MiB, the classic DDP bucket size


class Bucket(object):
    """One dtype-homogeneous gradient bucket of a fused/duplex step
    plan (``kind`` carries the fused-optimizer tag on the Trainer's
    local-update path; None on store-update/Module plans)."""
    __slots__ = ("indices", "kind", "dtype", "nbytes")

    def __init__(self, indices, kind, dtype, nbytes):
        self.indices = tuple(indices)
        self.kind = kind
        self.dtype = dtype
        self.nbytes = nbytes


def bucket_order():
    """GRAFT_BUCKET_ORDER: ``tape`` (default) packs buckets by reverse
    tape order — autograd stamps each hooked parameter's earliest tape
    position during the backward prescan, and parameters whose gradients
    finalize FIRST (the last-used layers) pack into the first buckets,
    so the first reduce goes on the wire earlier in the walk and the
    overlap window covers more of backward.  ``index`` reverts to plain
    parameter-index packing (the PR 4 behavior).  ``touch`` packs by the
    FORWARD first-touch order the compiled-step trace records
    (graftstep: ``Trainer.note_first_touch_order``) — pulls and buckets
    then mirror the order the next forward consumes weights in, which
    fronts the duplex pull pipeline's first-touch waits; params with no
    recorded touch yet pack after the touched ones in index order."""
    v = os.environ.get("GRAFT_BUCKET_ORDER", "tape").strip().lower()
    if v in ("index", "touch"):
        return v
    return "tape"


def overlap_pull_enabled(override=None):
    """GRAFT_OVERLAP_PULL (default on): overlap the update_on_kvstore
    weight pulls with the next forward (graftduplex).  Like
    GRAFT_OVERLAP, multi-host jobs must set it IDENTICALLY on every
    rank — the issue order of the pull collectives is part of the
    lockstep contract."""
    if override is not None:
        return bool(override)
    return os.environ.get("GRAFT_OVERLAP_PULL", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def plan_pull_groups(keys, nbytes_per_key, target):
    """Greedily group ``keys`` (index order) into pull groups of
    ~``target`` bytes — the per-bucket granularity of the async
    pull/broadcast when no bucket plan exists (the dist_async parameter
    service path).  Returns a list of key-lists covering every key."""
    if target <= 0:
        return [list(keys)] if keys else []
    groups, cur, cur_bytes = [], [], 0
    for k, nb in zip(keys, nbytes_per_key):
        cur.append(k)
        cur_bytes += nb
        if cur_bytes >= target:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        groups.append(cur)
    return groups


def publish_pull_round(sched):
    """Publish the PREVIOUS round's pull-overlap telemetry before a new
    round issues (the round's waits finished at first-touch during the
    last forward and in the consumer's finish() at step start)."""
    from .telemetry import metrics as _tmetrics
    n, exposed_s, inflight_s, stale_seen = sched.take_stats()
    if n:
        _tmetrics.trainer_pull_overlap(n, 0, exposed_s, inflight_s,
                                       stale=stale_seen)


def serial_pull(kv, keys, outs):
    """The synchronous batched pull, reported on the same pull telemetry
    (exposed == inflight) so serial and duplex runs stay comparable on
    one gauge."""
    from .telemetry import metrics as _tmetrics
    t0 = time.perf_counter()
    kv.pull_many(keys, outs)
    dt = time.perf_counter() - t0
    _tmetrics.trainer_pull_overlap(0, 1, dt, dt)


def pull_round(sched, kv, keys, outs, sizes, target, overlap):
    """One whole pull round, shared by ``gluon.Trainer._update`` and
    ``Module``'s update_on_kvstore path: publish the previous round,
    then either the serial batched pull (``overlap=False`` — the
    kill-switch / stale / sparse fallbacks) or async per ~``target``-byte
    group with first-touch waits.  ``outs[i]`` is the out-NDArray list
    (one per context replica) for ``keys[i]``; ``sizes[i]`` its payload
    bytes."""
    publish_pull_round(sched)
    if not overlap:
        serial_pull(kv, keys, outs)
        return
    by_key = dict(zip(keys, outs))
    for gkeys in plan_pull_groups(keys, sizes, target):
        sched.issue(kv, gkeys, [by_key[k] for k in gkeys],
                    label="pull[%dp]" % len(gkeys))


def concat_ctx_sum(grads_by_ctx, ctx=None):
    """One bucket's concatenated local gradient: per-context flatten
    (one jitted dispatch each) + elementwise context tree-sum in context
    order — THE packing math, shared verbatim by the serial step paths
    (Trainer and Module) and the overlapped mid-backward issue so all of
    them are bit-identical by construction.  ``grads_by_ctx`` is a list
    over contexts of equally-ordered gradient NDArray lists; replicas
    committed to distinct devices are colocated before the sum
    (transfers preserve bits)."""
    from .ndarray import NDArray
    per_ctx = [
        _engine.flatten_arrays(tuple(g._read() for g in ctx_grads))
        for ctx_grads in grads_by_ctx]
    acc = per_ctx[0]
    for f in per_ctx[1:]:
        acc = acc + _engine.colocate(f, acc)
    return NDArray(acc, ctx=ctx)


class BucketScheduler(object):
    """graftlap/graftduplex: issue each bucket's gradient allreduce
    DURING backward.

    Armed by the host's step with the current bucket plan, the scheduler
    hangs a grad-ready hook on every eligible gradient carrier (autograd
    fires it the moment that parameter's gradient is final — see
    ``autograd._run_backward``; ``symbol.Executor.backward`` fires the
    same hook as it writes each bound grad array).  When the last
    (param, context) pair of a bucket reports ready, the bucket's
    concatenated flat gradient is built with the host's OWN serial-path
    math (``_sched_flat``) and shipped through
    ``KVStore.reduce_many_async`` — an in-flight handle with its own
    flight-recorder bracket — while backward keeps producing
    earlier-layer gradients.  The host's step then only *waits* on the
    handles.  Because the hook order is the reverse-topological walk of
    a tape every rank shares (SPMD), the issue order of the collectives
    is identical on every worker: the lockstep contract holds.

    The host protocol (duck-typed; ``gluon.Trainer`` and
    ``module.Module`` implement it):

    * ``_sched_entries(bucket)`` → ``[(key, carrier, grad), ...]`` —
      the (param, context) keys of the bucket, the NDArray each hook
      sits on, and the gradient NDArray whose ``_version`` gates
      consumption;
    * ``_sched_eligible(bucket)`` → only ``grad_req == "write"`` buckets
      may arm ("add" accumulation means grads are not final per pass);
    * ``_sched_kv()`` / ``_sched_flat(bucket)`` / ``_sched_label(bucket)``;
    * ``_sched_pass_id()`` — a monotonic backward-pass id (autograd's
      for the Trainer, the executor group's backward counter for
      Module);
    * ``_sched_autograd_hooks`` — True when autograd delivers the hooks
      (the tape prescan is then gated on this scheduler's registration).

    Safety rails (each one degrades to the serial bucketed reduce,
    never to wrong values):

    * hooks fire only on a plain full backward — ``retain_graph``,
      ``create_graph`` and explicit-variables passes suppress them;
    * a hook under a NEW pass id abandons every handle of the previous
      pass before scheduling restarts (a second backward overwrote the
      reduced grads);
    * at consume time every grad's ``_version`` must still match its
      issue-time stamp (gradient clipping or any other post-backward
      mutation invalidates the handle);
    * a scheduler exception marks it broken for the step instead of
      propagating into the user's backward.
    """

    __slots__ = ("_host_ref", "_armed", "_waiting", "_hooked",
                 "_buckets", "_pass_id", "_broken", "_plan", "_hook",
                 "_fire_count", "issue_log", "issued_total", "taken_total",
                 "__weakref__")

    def __init__(self, host):
        self._host_ref = weakref.ref(host)
        # ONE hook closure, created once (`self._on_ready` builds a fresh
        # bound method per attribute access, so ad-hoc accessors would
        # never pass disarm's identity check and hooks would leak), and
        # holding the scheduler WEAKLY: a bound method would pin the
        # scheduler — and through nothing else, the arrays its hooks sit
        # on — alive long after the host is dropped, keeping the
        # autograd hook-source gate open forever.  With the weakref the
        # scheduler dies with its host; orphaned hook attrs left on
        # carrier arrays degrade to a dead-ref no-op until overwritten.
        sched_ref = weakref.ref(self)

        def _hook(arr, _ref=sched_ref):
            sched = _ref()
            if sched is not None:
                sched._on_ready(arr)
        self._hook = _hook
        self._armed = False
        self._waiting = {}      # id(carrier NDArray) -> (bucket state, key)
        self._hooked = []       # carrier NDArrays carrying our hook
        self._buckets = {}      # id(bucket) -> state dict
        self._pass_id = None
        self._broken = False
        self._plan = None       # the armed plan, held STRONGLY: identity
        #                         (same cached tuple) means same plan, and
        #                         the ref pins it so a recycled id() can
        #                         never alias a new plan
        self._fire_count = 0    # hooks consumed this pass (tape-order
        #                         evidence: how early each bucket closed)
        self.issue_log = []     # [(bucket indices, fire_count at issue)]
        #                         for the current pass
        self.issued_total = 0   # buckets issued mid-backward (ever)
        self.taken_total = 0    # issued buckets actually consumed by step

    # -- arming -------------------------------------------------------------
    def arm(self, plan):
        """Install hooks for ``plan``'s eligible buckets (called at the
        end of every overlapped step, so the NEXT backward schedules).
        Steady state — same (cached) plan object, scheduler healthy —
        skips the reinstall: the next backward's first hook resets the
        pending sets via the pass-id rollover, so re-arming is O(1)."""
        with _tsan.region(self, "arm"):
            self._arm(plan)

    def _arm(self, plan):
        if self._armed and not self._broken and self._plan is plan:
            self._abandon_all()
            for state in self._buckets.values():
                state["handle"] = None
                state["flat"] = None
            self._pass_id = None    # next hook rebuilds pending sets
            return
        self.disarm()
        host = self._host_ref()
        if host is None:
            return
        buckets, _leftover = plan
        for b in buckets:
            if not host._sched_eligible(b):
                continue        # "add" accumulation: never final per pass
            entries = host._sched_entries(b)
            if not entries:
                continue
            state = {"bucket": b, "pending": set(), "handle": None,
                     "flat": None, "versions": None,
                     "grads": [g for _k, _c, g in entries],
                     "all_keys": frozenset(k for k, _c, _g in entries)}
            for key, carrier, _grad in entries:
                state["pending"].add(key)
                self._waiting[id(carrier)] = (state, key)
                carrier._grad_ready_hook = self._hook
                self._hooked.append(carrier)
            self._buckets[id(b)] = state
        self._armed = bool(self._buckets)
        if self._armed and getattr(host, "_sched_autograd_hooks", True):
            from . import autograd
            autograd.register_hook_source(self)
        self._plan = plan if self._armed else None
        self._pass_id = None
        self._broken = False

    def disarm(self):
        """Drop hooks and abandon anything still in flight."""
        with _tsan.region(self, "disarm"):
            self._disarm()

    def _disarm(self):
        for d in self._hooked:
            if getattr(d, "_grad_ready_hook", None) is self._hook:
                d._grad_ready_hook = None
        self._hooked = []
        self._waiting = {}
        self._abandon_all()
        self._buckets = {}
        self._armed = False
        self._plan = None
        from . import autograd
        autograd.unregister_hook_source(self)

    def _abandon_all(self):
        for state in self._buckets.values():
            if state["handle"] is not None:
                state["handle"].abandon()
                state["handle"] = None

    # -- the hook (fires inside the host's backward) ------------------------
    def _on_ready(self, arr):
        # grafttsan region: the hook mutates pending sets / handles; a
        # consumer (arm/disarm/take) on another thread racing it is the
        # EH202 hazard.  Per-gradient hot path — the raw flag keeps the
        # disabled cost to one attribute load + index (the _write/_read
        # convention); the once-per-step entry points go through region()
        if _tsan._ACTIVE[0]:
            with _tsan.region(self, "_on_ready"):
                self._on_ready_locked(arr)
        else:
            self._on_ready_locked(arr)

    def _on_ready_locked(self, arr):
        if not self._armed or self._broken:
            return
        host = self._host_ref()
        if host is None:
            # the host is gone but something still holds the scheduler
            # (a kept `t._scheduler` ref): clean up after ourselves
            self.disarm()
            return
        try:
            pass_id = host._sched_pass_id()
            if pass_id != self._pass_id:
                # new backward pass: everything issued for the previous
                # one reduces grads that were just overwritten — discard
                # and start this pass clean
                self._abandon_all()
                for state in self._buckets.values():
                    state["pending"] = set(state["all_keys"])
                self._pass_id = pass_id
                self._fire_count = 0
                self.issue_log = []
            entry = self._waiting.get(id(arr))
            if entry is None:
                return
            state, key = entry
            self._fire_count += 1
            state["pending"].discard(key)
            if not state["pending"] and state["handle"] is None:
                self._issue(host, state)
        except Exception:
            self._broken = True
            self._abandon_all()
            raise               # _fire_ready_hook catches + logs; the
            #                     user's backward pass is unaffected

    def _issue(self, host, state):
        """All grads of one bucket are final: build the flat buffer and
        put its reduce on the wire, without joining (or flushing) any
        bulk segment the surrounding code has open."""
        kv = host._sched_kv()
        if kv is None:
            return
        b = state["bucket"]
        with _engine.offband():
            flat = host._sched_flat(b)
            state["versions"] = [g._version for g in state["grads"]]
            state["flat"] = flat
            # graftzero: hosts with a quantized-wire hook (Trainer) issue
            # the bucket through it — the scheduler itself is payload-
            # agnostic and issues quantized buckets unchanged
            issue = getattr(host, "_sched_reduce_async", None)
            if issue is not None:
                state["handle"] = issue(kv, b, flat)
            else:
                state["handle"] = kv.reduce_many_async(
                    [flat], label=host._sched_label(b))
        self.issue_log.append((b.indices, self._fire_count))
        self.issued_total += 1
        # graftpulse memory timeline: the mid-backward issue is where a
        # bucket's flat buffer peaks — sample the watermark per bucket
        from .telemetry import lens as _lens
        _lens.mem_sample(host._sched_label(b))

    # -- consuming (the host's step) ----------------------------------------
    def take(self, plan):
        """Hand the step the buckets whose reduces are validly in flight:
        ``{id(bucket): (flat NDArray, ReduceHandle)}``.  Stale handles
        (grad versions moved since issue) are abandoned; everything is
        one-shot — the caller re-arms for the next step."""
        with _tsan.region(self, "take"):
            return self._take(plan)

    def _take(self, plan):
        out = {}
        if self._host_ref() is None or not self._armed or self._broken:
            self._abandon_all()
            return out
        buckets, _leftover = plan
        by_id = {id(b): b for b in buckets}
        for bid, state in self._buckets.items():
            handle = state["handle"]
            if handle is None:
                continue
            b = by_id.get(bid)
            if b is None:
                handle.abandon()        # plan changed under us
                continue
            if [g._version for g in state["grads"]] != state["versions"]:
                handle.abandon()        # stale grads: serial fallback
                continue
            out[bid] = (state["flat"], handle)
            state["handle"] = None      # consumed
        self.taken_total += len(out)
        return out


class PullScheduler(object):
    """graftduplex pull side: in-flight weight pulls waited at FIRST USE.

    ``issue`` puts one group's pull on the wire
    (``KVStore.pull_many_async``) and installs a first-touch hook on
    every out array (``NDArray._touch_hook``, checked at the top of
    ``_read``) — the next forward's first read of ANY covered weight
    waits that group's handle before the value is returned, so a
    read-modify-write between steps (`w *= 0.5`) sees the pulled bytes
    exactly as the serial pull-then-mutate ordering would.  A direct
    overwrite without a read bumps the array's ``_version`` past the
    issue-time stamp: the pulled value for that array is dropped (the
    user's write wins — again the serial ordering) and the round is
    flagged stale, which consumers answer with one serial-pull round
    (abandon-and-fallback, mirroring the reduce side's stale-grad rail).
    ``finish()`` — called at the start of the next step — waits whatever
    the forward never touched, so no handle outlives its step."""

    __slots__ = ("_hook", "_groups", "_by_arr", "issued_total",
                 "touched_total", "finished_total", "stale_total",
                 "exposed_s", "inflight_s", "__weakref__")

    def __init__(self):
        sched_ref = weakref.ref(self)

        def _hook(arr, _ref=sched_ref):
            sched = _ref()
            if sched is None:
                arr._touch_hook = None      # dead scheduler: self-clean
                return
            sched._on_touch(arr)
        self._hook = _hook
        self._groups = {}       # id(group) -> group dict
        self._by_arr = {}       # id(out NDArray) -> group
        self.issued_total = 0   # groups ever issued
        self.touched_total = 0  # groups finished by a first-touch read
        self.finished_total = 0     # groups finished since take_stats
        self.stale_total = 0        # stale outs since take_stats
        self.exposed_s = 0.0        # blocked wait since take_stats
        self.inflight_s = 0.0       # issue→wait-return since take_stats

    @property
    def inflight_groups(self):
        return len(self._groups)

    def issue(self, kv, keys, outs, label=None):
        """Put one group's pull on the wire; ``outs`` is a list (per
        key) of out-NDArray lists (one per context replica)."""
        # graftarmor chaos site: the duplex pull-issue edge (error here
        # models a wire that dies between step N's update and step N+1's
        # prefetch — the consumer's abandon-and-fallback rail)
        from .armor import faults as _faults
        _faults.fault_point("overlap.pull_issue", n_keys=len(keys),
                            bucket=label)
        with _tsan.region(self, "issue"):
            return self._issue(kv, keys, outs, label=label)

    def _issue(self, kv, keys, outs, label=None):
        flat = [o for olist in outs for o in olist]
        for o in flat:
            g = self._by_arr.get(id(o))
            if g is not None:
                self._finish_group(g)   # an array rides ONE group at a
                #                         time (callers finish() first;
                #                         this is the defensive rail)
        handle = kv.pull_many_async(keys, outs, label=label)
        group = {"handle": handle, "outs": flat,
                 "versions": [o._version for o in flat]}
        self._groups[id(group)] = group
        for o in flat:
            self._by_arr[id(o)] = group
            o._touch_hook = self._hook
        self.issued_total += 1
        return handle

    # -- the first-touch hook (fires inside NDArray._read) ------------------
    def _on_touch(self, arr):
        # same single-owner contract as the reduce side's _on_ready: a
        # first-touch hook racing issue/finish from another thread is
        # EH202 under GRAFT_TSAN (raw-flag guard: this sits inside the
        # _read hot path)
        if _tsan._ACTIVE[0]:
            with _tsan.region(self, "_on_touch"):
                self._on_touch_locked(arr)
        else:
            self._on_touch_locked(arr)

    def _on_touch_locked(self, arr):
        arr._touch_hook = None
        group = self._by_arr.get(id(arr))
        if group is None:
            return
        self.touched_total += 1
        self._finish_group(group)

    def _finish_group(self, group):
        # clear the group's hooks FIRST: handle.wait() reads the out
        # arrays, and a still-hooked sibling would re-enter this path
        # mid-wait
        for o in group["outs"]:
            if getattr(o, "_touch_hook", None) is self._hook:
                o._touch_hook = None
            self._by_arr.pop(id(o), None)
        self._groups.pop(id(group), None)
        handle = group["handle"]
        stale = sum(1 for o, v in zip(group["outs"], group["versions"])
                    if o._version != v)
        handle.wait()       # PS handles apply version-gated writes here;
        #                     in-process handles wrote at issue (any later
        #                     user write already sits on top — serial
        #                     order) and only block-until-ready
        self.stale_total += max(stale, getattr(handle, "stale", 0))
        self.exposed_s += handle.blocked_s
        self.inflight_s += handle.inflight_s
        self.finished_total += 1

    # -- consumer API --------------------------------------------------------
    def finish(self):
        """Wait every outstanding group (called before issuing the next
        round, and by teardown).  Returns the stale-out count observed
        since the last :meth:`take_stats` — nonzero means the consumer
        should run the NEXT round serial (abandon-and-fallback)."""
        with _tsan.region(self, "finish"):
            for group in list(self._groups.values()):
                self._finish_group(group)
            return self.stale_total

    def abandon_all(self):
        """Drop every outstanding group without consuming (teardown
        fallback): hooks clear, brackets close, deferred writes (the PS
        path) are lost — only reached when waiting is no longer safe."""
        with _tsan.region(self, "abandon_all"):
            self._abandon_all()

    def _abandon_all(self):
        for group in list(self._groups.values()):
            for o in group["outs"]:
                if getattr(o, "_touch_hook", None) is self._hook:
                    o._touch_hook = None
                self._by_arr.pop(id(o), None)
            group["handle"].abandon()
        self._groups = {}

    def __del__(self):
        # a consumer dropped with pulls in flight must not leak open
        # flight-recorder brackets (they would sit in every later crash
        # dump as phantom in-flight collectives): settle them — waiting
        # applies any deferred PS writes the out arrays still expect
        try:
            self.finish()
        except Exception:
            try:
                self.abandon_all()
            except Exception:
                pass        # interpreter teardown: nothing to save

    def take_stats(self):
        """(groups, exposed_s, inflight_s, stale) accumulated since the
        last call — the consumer publishes them as the pull-overlap
        telemetry round."""
        out = (self.finished_total, self.exposed_s, self.inflight_s,
               self.stale_total)
        self.finished_total = 0
        self.stale_total = 0
        self.exposed_s = 0.0
        self.inflight_s = 0.0
        return out
