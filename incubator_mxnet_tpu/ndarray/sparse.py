"""Sparse NDArrays: RowSparse and CSR.

TPU-native design for the reference's first-class sparse storage types
(include/mxnet/ndarray.h:61-65, src/operator/tensor/cast_storage*,
dot-inl.h sparse kernels).  XLA has no native sparse tensors, so — per
SURVEY §7 hard part #3 — sparse arrays here are *structs of dense device
arrays* (values + indices), with compute lowered to gather/scatter/segment
ops that XLA maps well to TPU (dense row gathers feed the MXU; scatters use
sorted segment sums).  The API (stype, .data/.indices/.indptr, tostype,
retain) matches python/mxnet/ndarray/sparse.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros", "retain"]


class BaseSparseNDArray(NDArray):
    """Common surface for sparse arrays (parity: sparse.py BaseSparseNDArray)."""

    def __init__(self, shape, ctx=None):
        # no dense root buffer; subclasses hold component NDArrays
        super().__init__(data=None, ctx=ctx)
        self._shape = tuple(shape)

    def _read(self):
        return self.todense()._read()

    def _write(self, value):
        raise TypeError("in-place writes on sparse NDArray are not supported")

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        return cast_storage(self, stype)


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse: (indices[K], values[K, ...]) — K occupied rows.

    ref: python/mxnet/ndarray/sparse.py RowSparseNDArray; used for sparse
    gradients of Embedding/FullyConnected and KVStore row_sparse_pull.
    """

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(shape, ctx=ctx)
        self.data = data          # NDArray (K, *shape[1:])
        self.indices = indices    # NDArray (K,) int64, sorted unique

    @property
    def stype(self):
        return "row_sparse"

    def todense(self):
        dense = jnp.zeros(self._shape, self.data._read().dtype)
        idx = self.indices._read().astype(jnp.int32)
        dense = dense.at[idx].set(self.data._read())
        return NDArray(dense, ctx=self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray) and not isinstance(other, BaseSparseNDArray):
            other._write(self.todense()._read())
            return other
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, ctx=self._ctx)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: sparse.py CSRNDArray)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(shape, ctx=ctx)
        self.data = data        # (nnz,)
        self.indices = indices  # (nnz,) column ids
        self.indptr = indptr    # (rows+1,)

    @property
    def stype(self):
        return "csr"

    def todense(self):
        m, n = self._shape
        d = self.data._read()
        col = self.indices._read().astype(jnp.int32)
        ptr = self.indptr._read().astype(jnp.int32)
        # row id per nnz via searchsorted on indptr
        nnz = d.shape[0]
        row = jnp.searchsorted(ptr, jnp.arange(nnz), side="right") - 1
        dense = jnp.zeros((m, n), d.dtype).at[row, col].set(d)
        return NDArray(dense, ctx=self._ctx)

    def __getitem__(self, key):
        """Row slicing stays CSR (ref: sparse.py CSRNDArray.__getitem__ —
        the reference supports basic slicing on csr; needed e.g. by
        DataParallelExecutorGroup splitting a LibSVMIter batch across
        contexts)."""
        if isinstance(key, int):
            if key < 0:
                key += self._shape[0]
            if not 0 <= key < self._shape[0]:
                raise IndexError(
                    "index %r is out of bounds for axis 0 with size %d"
                    % (key, self._shape[0]))
            key = slice(key, key + 1)
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise ValueError(
                "CSRNDArray only supports contiguous row slicing, got %r"
                % (key,))
        start, stop, _ = key.indices(self._shape[0])
        stop = max(start, stop)  # empty, not negative-row-count, for csr[3:1]
        ptr = np.asarray(self.indptr._read())
        lo, hi = int(ptr[start]), int(ptr[stop])
        new_ptr = ptr[start:stop + 1] - ptr[start]
        return CSRNDArray(
            NDArray(self.data._read()[lo:hi], ctx=self._ctx),
            NDArray(self.indices._read()[lo:hi], ctx=self._ctx),
            NDArray(jnp.asarray(new_ptr), ctx=self._ctx),
            (stop - start, self._shape[1]), ctx=self._ctx)

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.py row_sparse_array — from (data, indices) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else _dense_array(np.asarray(data), ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            np.asarray(indices), ctx=ctx, dtype=np.int64)
        if shape is None:
            raise ValueError("shape required when building from (data, indices)")
        return RowSparseNDArray(data, indices, tuple(shape), ctx=ctx)
    # dense source
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return cast_storage(_dense_array(src, ctx=ctx, dtype=dtype), "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.py csr_matrix — from (data, indices, indptr) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        mk = lambda x, dt=None: x if isinstance(x, NDArray) else _dense_array(
            np.asarray(x), ctx=ctx, dtype=dt)
        if shape is None:
            raise ValueError("shape required")
        return CSRNDArray(mk(data, dtype), mk(indices, np.int64),
                          mk(indptr, np.int64), tuple(shape), ctx=ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return cast_storage(_dense_array(src, ctx=ctx, dtype=dtype), "csr")


def cast_storage(arr, stype):
    """ref: src/operator/tensor/cast_storage.cc — dense↔rsp↔csr."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if isinstance(arr, BaseSparseNDArray):
        arr = arr.todense()
    a = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = np.where(np.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(
            _dense_array(a[nz_rows], ctx=arr._ctx),
            _dense_array(nz_rows.astype(np.int64), ctx=arr._ctx, dtype=np.int64),
            a.shape, ctx=arr._ctx)
    if stype == "csr":
        if a.ndim != 2:
            raise ValueError("csr requires 2-D")
        rows, cols = np.nonzero(a)
        indptr = np.zeros(a.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(
            _dense_array(a[rows, cols], ctx=arr._ctx),
            _dense_array(cols.astype(np.int64), ctx=arr._ctx, dtype=np.int64),
            _dense_array(indptr, ctx=arr._ctx, dtype=np.int64),
            a.shape, ctx=arr._ctx)
    raise ValueError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """ref: sparse.py zeros"""
    ctx = ctx or current_context()
    if stype == "row_sparse":
        return RowSparseNDArray(
            _dense_array(np.zeros((0,) + tuple(shape[1:]), dtype), ctx=ctx),
            _dense_array(np.zeros((0,), np.int64), ctx=ctx, dtype=np.int64),
            tuple(shape), ctx=ctx)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(np.zeros((0,), dtype), ctx=ctx),
            _dense_array(np.zeros((0,), np.int64), ctx=ctx, dtype=np.int64),
            _dense_array(np.zeros((shape[0] + 1,), np.int64), ctx=ctx, dtype=np.int64),
            tuple(shape), ctx=ctx)
    raise ValueError("unknown stype %r" % stype)


def retain(arr, row_ids):
    """Keep only given rows (ref: src/operator/tensor/sparse_retain.cc)."""
    if not isinstance(arr, RowSparseNDArray):
        raise TypeError("retain expects RowSparseNDArray")
    want = row_ids.asnumpy().astype(np.int64) if isinstance(row_ids, NDArray) else np.asarray(row_ids, np.int64)
    have = arr.indices.asnumpy()
    mask = np.isin(have, want)
    keep = np.where(mask)[0]
    return RowSparseNDArray(
        NDArray(arr.data._read()[jnp.asarray(keep, jnp.int32)], ctx=arr._ctx),
        _dense_array(have[keep], ctx=arr._ctx, dtype=np.int64),
        arr.shape, ctx=arr._ctx)


# ---------------------------------------------------------------------------
# Sparse compute (ref: src/operator/tensor/dot-inl.h sparse kernels,
# elemwise ops with FComputeEx) — gather/segment-sum formulations that XLA
# lowers to TPU-friendly dense gathers + sorted scatters.
# ---------------------------------------------------------------------------

from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(4,))
def _csr_matmul(data, col, row, rhs, m):
    """CSR(m×k) @ dense(k×n) with differentiable data/rhs."""
    contrib = data[:, None] * rhs[col]                  # (nnz, n)
    return jax.ops.segment_sum(contrib, row, num_segments=m)


def _csr_matmul_fwd(data, col, row, rhs, m):
    return _csr_matmul(data, col, row, rhs, m), (data, col, row, rhs)


def _csr_matmul_bwd(m, res, g):
    data, col, row, rhs = res
    d_data = (g[row] * rhs[col]).sum(axis=1)
    d_rhs = jax.ops.segment_sum(data[:, None] * g[row], col,
                                num_segments=rhs.shape[0])
    return (d_data, None, None, d_rhs)


_csr_matmul.defvjp(_csr_matmul_fwd, _csr_matmul_bwd)


def _csr_row_ids(csr):
    ptr = csr.indptr._read().astype(jnp.int32)
    nnz = csr.data._read().shape[0]
    return jnp.searchsorted(ptr, jnp.arange(nnz), side="right") - 1


def _dense_operand_op(name, fn_dense, rhs, ctx):
    """Run a sparse kernel that is differentiable in its DENSE operand and
    RECORD it on the autograd tape (the hand-rolled sparse paths bypass
    ndarray.invoke, so without this the tape silently treated their
    outputs as constants — zero gradient to the dense weight, the exact
    case the reference's csr-dot backward serves, dot-inl.h backward).
    Gradients w.r.t. the sparse operand itself stay unsupported (parity:
    the reference likewise differentiates only the dense side)."""
    from .. import autograd
    if autograd.is_recording():
        out_val, vjp_fn = jax.vjp(fn_dense, rhs._read())
        out_nd = NDArray(out_val, ctx=ctx)
        from ..ops.registry import Operator
        op = Operator(name, fn_dense, num_inputs=1)
        autograd._record(op, [rhs], [out_nd], vjp_fn, fn=fn_dense)
        return out_nd
    return NDArray(fn_dense(rhs._read()), ctx=ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (ref: dot-inl.h — csr×dense and csrᵀ×dense kernels;
    python surface mx.nd.sparse.dot)."""
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray):
        row = _csr_row_ids(lhs)
        col = lhs.indices._read().astype(jnp.int32)
        data = lhs.data._read()
        if transpose_a:
            # csrᵀ @ dense: scatter rows of dense by col
            def fn(r_, data=data, row=row, col=col, n=lhs.shape[1]):
                if transpose_b:
                    r_ = r_.T
                return jax.ops.segment_sum(data[:, None] * r_[row], col,
                                           num_segments=n)
            return _dense_operand_op("_sparse_dot_csrT", fn, rhs, lhs._ctx)

        def fn(r_, data=data, row=row, col=col, m=lhs.shape[0]):
            if transpose_b:
                r_ = r_.T
            return _csr_matmul(data, col, row, r_, m)
        return _dense_operand_op("_sparse_dot_csr", fn, rhs, lhs._ctx)
    if isinstance(lhs, RowSparseNDArray) and not isinstance(rhs, BaseSparseNDArray):
        if transpose_a or transpose_b:
            # no transposed rsp kernel (parity: dot-inl.h only dispatches
            # csr for transposed sparse dots) — densify rather than be wrong
            return dot(NDArray(lhs.todense()._read(), ctx=lhs._ctx), rhs,
                       transpose_a=transpose_a, transpose_b=transpose_b)
        # rsp @ dense: dense rows gather-matmul, scatter into result
        idx = lhs.indices._read().astype(jnp.int32)
        ldata = lhs.data._read()

        def fn(r_, idx=idx, ldata=ldata, m=lhs.shape[0]):
            out = jnp.zeros((m, r_.shape[1]), ldata.dtype)
            return out.at[idx].set(ldata @ r_)
        return _dense_operand_op("_sparse_dot_rsp", fn, rhs, lhs._ctx)
    if isinstance(rhs, RowSparseNDArray):
        # dense @ rsp has no sparse kernel either way — densify rhs
        return dot(lhs, NDArray(rhs.todense()._read(), ctx=rhs._ctx),
                   transpose_a=transpose_a, transpose_b=transpose_b)
    if isinstance(rhs, BaseSparseNDArray):
        # op(dense) @ op(csr) = (op(csr)ᵀ @ op(dense)ᵀ)ᵀ; op(dense)ᵀ is
        # lhs itself when transpose_a is set, lhsᵀ otherwise
        lt = lhs._read() if transpose_a else lhs._read().T
        return NDArray(dot(rhs, NDArray(lt, ctx=lhs._ctx),
                           transpose_a=not transpose_b)._read().T,
                       ctx=lhs._ctx)
    from .ndarray import invoke
    from ..ops.registry import get_op
    return invoke(get_op("dot"), [lhs, rhs],
                  {"transpose_a": transpose_a, "transpose_b": transpose_b})


def elemwise_add(lhs, rhs):
    """rsp+rsp → rsp (ref: elemwise_binary_op FComputeEx rsp,rsp)."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        idx = jnp.concatenate([lhs.indices._read(), rhs.indices._read()])
        vals = jnp.concatenate([lhs.data._read(), rhs.data._read()])
        uniq, inv = jnp.unique(idx, return_inverse=True,
                               size=idx.shape[0], fill_value=lhs.shape[0])
        summed = jax.ops.segment_sum(vals, inv.astype(jnp.int32),
                                     num_segments=idx.shape[0])
        keep = uniq < lhs.shape[0]
        k = int(keep.sum())
        return RowSparseNDArray(
            NDArray(summed[:k], ctx=lhs._ctx),
            NDArray(uniq[:k].astype(jnp.int64), ctx=lhs._ctx),
            lhs.shape, ctx=lhs._ctx)
    a = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return a + b


def add_n(*arrays):
    """Sum of sparse/dense arrays (ref: elemwise_sum FComputeEx)."""
    acc = arrays[0]
    for a in arrays[1:]:
        acc = elemwise_add(acc, a)
    return acc
