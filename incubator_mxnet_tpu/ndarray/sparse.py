"""Sparse NDArrays: RowSparse and CSR.

TPU-native design for the reference's first-class sparse storage types
(include/mxnet/ndarray.h:61-65, src/operator/tensor/cast_storage*,
dot-inl.h sparse kernels).  XLA has no native sparse tensors, so — per
SURVEY §7 hard part #3 — sparse arrays here are *structs of dense device
arrays* (values + indices), with compute lowered to gather/scatter/segment
ops that XLA maps well to TPU (dense row gathers feed the MXU; scatters use
sorted segment sums).  The API (stype, .data/.indices/.indptr, tostype,
retain) matches python/mxnet/ndarray/sparse.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage", "zeros", "retain"]


class BaseSparseNDArray(NDArray):
    """Common surface for sparse arrays (parity: sparse.py BaseSparseNDArray)."""

    def __init__(self, shape, ctx=None):
        # no dense root buffer; subclasses hold component NDArrays
        super().__init__(data=None, ctx=ctx)
        self._shape = tuple(shape)

    def _read(self):
        return self.todense()._read()

    def _write(self, value):
        raise TypeError("in-place writes on sparse NDArray are not supported")

    @property
    def dtype(self):
        return self.data.dtype

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        return cast_storage(self, stype)


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse: (indices[K], values[K, ...]) — K occupied rows.

    ref: python/mxnet/ndarray/sparse.py RowSparseNDArray; used for sparse
    gradients of Embedding/FullyConnected and KVStore row_sparse_pull.
    """

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(shape, ctx=ctx)
        self.data = data          # NDArray (K, *shape[1:])
        self.indices = indices    # NDArray (K,) int64, sorted unique

    @property
    def stype(self):
        return "row_sparse"

    def todense(self):
        dense = jnp.zeros(self._shape, self.data._read().dtype)
        idx = self.indices._read().astype(jnp.int32)
        dense = dense.at[idx].set(self.data._read())
        return NDArray(dense, ctx=self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray) and not isinstance(other, BaseSparseNDArray):
            other._write(self.todense()._read())
            return other
        return RowSparseNDArray(self.data.copy(), self.indices.copy(),
                                self._shape, ctx=self._ctx)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: sparse.py CSRNDArray)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(shape, ctx=ctx)
        self.data = data        # (nnz,)
        self.indices = indices  # (nnz,) column ids
        self.indptr = indptr    # (rows+1,)

    @property
    def stype(self):
        return "csr"

    def todense(self):
        m, n = self._shape
        d = self.data._read()
        col = self.indices._read().astype(jnp.int32)
        ptr = self.indptr._read().astype(jnp.int32)
        # row id per nnz via searchsorted on indptr
        nnz = d.shape[0]
        row = jnp.searchsorted(ptr, jnp.arange(nnz), side="right") - 1
        dense = jnp.zeros((m, n), d.dtype).at[row, col].set(d)
        return NDArray(dense, ctx=self._ctx)

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % (
            "x".join(str(s) for s in self._shape), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.py row_sparse_array — from (data, indices) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else _dense_array(np.asarray(data), ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else _dense_array(
            np.asarray(indices), ctx=ctx, dtype=np.int64)
        if shape is None:
            raise ValueError("shape required when building from (data, indices)")
        return RowSparseNDArray(data, indices, tuple(shape), ctx=ctx)
    # dense source
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return cast_storage(_dense_array(src, ctx=ctx, dtype=dtype), "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """ref: sparse.py csr_matrix — from (data, indices, indptr) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        mk = lambda x, dt=None: x if isinstance(x, NDArray) else _dense_array(
            np.asarray(x), ctx=ctx, dtype=dt)
        if shape is None:
            raise ValueError("shape required")
        return CSRNDArray(mk(data, dtype), mk(indices, np.int64),
                          mk(indptr, np.int64), tuple(shape), ctx=ctx)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    return cast_storage(_dense_array(src, ctx=ctx, dtype=dtype), "csr")


def cast_storage(arr, stype):
    """ref: src/operator/tensor/cast_storage.cc — dense↔rsp↔csr."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if isinstance(arr, BaseSparseNDArray):
        arr = arr.todense()
    a = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = np.where(np.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(
            _dense_array(a[nz_rows], ctx=arr._ctx),
            _dense_array(nz_rows.astype(np.int64), ctx=arr._ctx, dtype=np.int64),
            a.shape, ctx=arr._ctx)
    if stype == "csr":
        if a.ndim != 2:
            raise ValueError("csr requires 2-D")
        rows, cols = np.nonzero(a)
        indptr = np.zeros(a.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(
            _dense_array(a[rows, cols], ctx=arr._ctx),
            _dense_array(cols.astype(np.int64), ctx=arr._ctx, dtype=np.int64),
            _dense_array(indptr, ctx=arr._ctx, dtype=np.int64),
            a.shape, ctx=arr._ctx)
    raise ValueError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """ref: sparse.py zeros"""
    ctx = ctx or current_context()
    if stype == "row_sparse":
        return RowSparseNDArray(
            _dense_array(np.zeros((0,) + tuple(shape[1:]), dtype), ctx=ctx),
            _dense_array(np.zeros((0,), np.int64), ctx=ctx, dtype=np.int64),
            tuple(shape), ctx=ctx)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(np.zeros((0,), dtype), ctx=ctx),
            _dense_array(np.zeros((0,), np.int64), ctx=ctx, dtype=np.int64),
            _dense_array(np.zeros((shape[0] + 1,), np.int64), ctx=ctx, dtype=np.int64),
            tuple(shape), ctx=ctx)
    raise ValueError("unknown stype %r" % stype)


def retain(arr, row_ids):
    """Keep only given rows (ref: src/operator/tensor/sparse_retain.cc)."""
    if not isinstance(arr, RowSparseNDArray):
        raise TypeError("retain expects RowSparseNDArray")
    want = row_ids.asnumpy().astype(np.int64) if isinstance(row_ids, NDArray) else np.asarray(row_ids, np.int64)
    have = arr.indices.asnumpy()
    mask = np.isin(have, want)
    keep = np.where(mask)[0]
    return RowSparseNDArray(
        NDArray(arr.data._read()[jnp.asarray(keep, jnp.int32)], ctx=arr._ctx),
        _dense_array(have[keep], ctx=arr._ctx, dtype=np.int64),
        arr.shape, ctx=arr._ctx)
