"""NDArray: the mutable n-dimensional array over immutable XLA buffers.

TPU-native rebirth of include/mxnet/ndarray.h + src/ndarray/ndarray.cc:

* The reference's ``Chunk`` (storage handle + engine variable) becomes a
  root ``jax.Array`` plus a monotonically increasing version counter — the
  version counter is the dependency-engine variable reborn (SURVEY §7 hard
  part #1).  In-place ops swap the root buffer and bump the version.
* Views (``Slice``/``At``/``Reshape``, ndarray.h:523) are (base, elem-offset,
  shape) triples — exactly the contiguous row-major views the reference
  supports — that re-materialize lazily when the base version moves, and
  write through with a scatter into the base buffer.
* Async semantics: every op call is an XLA async dispatch; ``wait_to_read``/
  ``waitall`` map to ``jax.block_until_ready`` — the WaitToRead/WaitForAll
  contract of the engine (include/mxnet/engine.h) holds verbatim.
* ``asnumpy`` is the sync point, as in the reference (ndarray.h:304).
"""
from __future__ import annotations

import time as _time
import weakref

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..ops.registry import get_op, Operator
from .. import random_state
from .. import config as _config
from ..analysis import tsan as _tsan
from ..analysis import compile_safety as _csafety
from ..telemetry import lens as _lens

# MXTPU_ENGINE_TYPE=NaiveEngine → block after every dispatch (the
# reference's synchronous debug engine, src/engine/naive_engine.cc);
# read once at import like dmlc::GetEnv's static locals.
_NAIVE_ENGINE = _config.naive_engine()

# devices that have received dispatches, for waitall() (WaitForAll):
# XLA executes compute in dispatch order per device stream, so enqueueing
# a trivial computation and blocking on it drains everything before it —
# a stream barrier, with no output buffers pinned.
_DISPATCH_DEVICES = set()

__all__ = ["NDArray", "array", "empty", "invoke", "waitall",
           "concatenate", "moveaxis", "imperative_invoke"]


def _default_dtype_for(source):
    if isinstance(source, np.ndarray):
        if source.dtype == np.float64 and not jax.config.jax_enable_x64:
            return np.float32
        return source.dtype
    return np.float32


class NDArray:
    """Mutable array handle (parity: python/mxnet/ndarray/ndarray.py NDArray)."""

    __array_priority__ = 1000.0  # beat numpy in mixed expressions

    # graftduplex first-touch hook: set per-instance by overlap.
    # PullScheduler on arrays with an async weight pull in flight; the
    # FIRST read waits the pull before the value escapes.  A class-level
    # default keeps the hot-path check in _read to one attribute load
    # that normally resolves here (None).
    _touch_hook = None

    def __init__(self, data=None, ctx=None, base=None, offset=0, shape=None):
        self._ctx = ctx if ctx is not None else current_context()
        if base is not None:
            # view
            self._base = base
            self._offset = int(offset)
            self._shape = tuple(shape)
            self._data = None
            self._cache_version = -1
            base._register_view(self)
        else:
            self._base = None
            self._offset = 0
            self._data = data
            self._shape = tuple(data.shape) if data is not None else None
            self._cache_version = 0
        self._version = 0
        # autograd state
        self._grad = None
        self._grad_req = "null"
        self._tape_ref = None  # (TapeNode, out_index) set by autograd

    # -- storage access ----------------------------------------------------
    def _root(self):
        return self._base if self._base is not None else self

    # -- view-group bookkeeping --------------------------------------------
    # Every root tracks weakrefs to the views cut from it, so base+views
    # form an inspectable OWNERSHIP GROUP: the strict-mode engine verifier
    # (GRAFT_ENGINE_CHECK=1, engine.py) walks the group to report which
    # sibling extracts a hazardous rebind invalidated, and liveness
    # debugging can enumerate who still exposes a buffer.  A plain list of
    # weakrefs, NOT a WeakSet: NDArray.__eq__ is elementwise broadcast, so
    # any hash-bucket collision inside a WeakSet would try to truth-test
    # an array.
    def _register_view(self, view):
        views = getattr(self, "_views", None)
        if views is None:
            views = self._views = []
        views.append(weakref.ref(view))
        # amortized O(1) on the hot __getitem__/reshape path: compact the
        # dead refs only once the list doubles past the last compaction
        if len(views) >= getattr(self, "_views_compact_at", 32):
            views[:] = [w for w in views if w() is not None]
            self._views_compact_at = max(32, 2 * len(views))

    def _live_views(self):
        """Live view NDArrays cut from this root (empty for views)."""
        views = getattr(self, "_views", None)
        if not views:
            return ()
        alive = [w() for w in views]
        views[:] = [w for w, v in zip(views, alive) if v is not None]
        return tuple(v for v in alive if v is not None)

    def _view_group(self):
        """(root, live views of that root) — the ownership group this
        array belongs to, whichever side of the base/view split it is."""
        root = self._root()
        return root, root._live_views()

    def _read(self, cause="read"):
        """Current jax.Array value (no host sync).  ``cause`` labels any
        flush this read forces: "read" for direct host reads of deferred
        values, "view" only when the _read_deferred fallback lands here
        after a view failed to defer."""
        th = self._touch_hook
        if th is not None:
            # first use of a weight with an async pull in flight: the
            # hook clears itself, then waits the pull group so the value
            # returned below is the pulled one (graftduplex)
            th(self)
        if _tsan._ACTIVE[0]:
            _tsan.on_read(self)     # EH204 for tracked shared arrays
        if _csafety._POISON and id(self) in _csafety._POISON:
            # graftguard EH302 donated-buffer read poison.  Gated on the
            # poison map rather than the armed flag: the map is only
            # populated inside an armed dispatch window, so the armed
            # steady-state read cost outside the window is the same one
            # truthiness check the disabled path pays
            _csafety.on_read(self)
        eng = _engine_mod()
        if self._base is None:
            if type(self._data) is eng._Pending:
                self._data = eng.resolve(self._data, cause=cause)
            return self._data
        b = self._base
        bth = b._touch_hook
        if bth is not None:
            # a view read IS a first use of its base: the slice below
            # reads b._data, so a pending pull on the base must land
            # first (the dist_async path defers its writes to wait time)
            bth(b)
        if (type(self._data) is eng._Pending
                and self._cache_version == b._version):
            # a deferred view extraction for the current base version:
            # resolving it flushes the shared segment (base fills too)
            self._data = eng.resolve(self._data, cause=cause)
            return self._data
        if type(b._data) is eng._Pending:
            b._data = eng.resolve(b._data, cause=cause)
        if self._cache_version != b._version or self._data is None \
                or type(self._data) is eng._Pending:
            flat = b._data.reshape((-1,))
            size = int(np.prod(self._shape)) if self._shape else 1
            self._data = jax.lax.slice(flat, (self._offset,), (self._offset + size,)).reshape(self._shape)
            self._cache_version = b._version
        return self._data

    def _read_deferred(self):
        """Like _read, but inside an active bulk scope an unresolved
        deferred value is returned as its _Pending placeholder so op
        chains keep deferring (engine.py maybe_defer).  A view over a
        deferred base becomes a recorded ``_bulk_view_extract`` pending
        (round 6) instead of a materialization point."""
        eng = _engine_mod()
        d = self._data
        if self._base is None:
            if type(d) is eng._Pending and d.value is None:
                return d
            return self._read()
        b = self._base
        if type(b._data) is eng._Pending and b._data.value is None:
            if (type(d) is eng._Pending and d.value is None
                    and self._cache_version == b._version):
                return d            # extraction already recorded this epoch
            p = eng.defer_view_read(self)
            if p is not None:
                self._data = p
                self._cache_version = b._version
                return p
            # deferral failed (cross-scope base …): this flush IS view
            # fragmentation — attribute it so the counters catch it
            return self._read(cause="view")
        return self._read()

    def _write(self, value):
        """Replace contents (in-place semantics; bumps the version 'var').

        ``value`` may be a _Pending (deferred op output): roots simply
        rebind to it, and a view over a deferred base records the
        write-through as a ``_bulk_view_write`` node so the whole
        read-modify-write stays in one segment."""
        if _tsan._ACTIVE[0]:
            # grafttsan: a cross-thread write to an array an async
            # reduce/pull handle still holds (EH201), or to a tracked
            # shared array without a happens-before edge (EH204).  The
            # raw flag (not enabled()) keeps the disabled cost of this
            # hot path to one attribute load + index
            _tsan.on_write(self)
        if _csafety._POISON:
            # graftguard EH302: a replacement landing re-arms a donated
            # buffer (map-truthiness gate, see the _read hook above)
            _csafety.on_write(self)
        eng = _engine_mod()
        if type(value) is eng._Pending:
            value.owners.append(weakref.ref(self))
        if self._base is None:
            self._data = value
            self._version += 1
            return
        b = self._base
        newbase = eng.defer_view_write(self, value)
        if newbase is None:
            # non-deferrable write-through: any flush these resolves force
            # is view fragmentation
            if type(value) is eng._Pending:
                value = eng.resolve(value, cause="view")
            if type(b._data) is eng._Pending:
                b._data = eng.resolve(b._data, cause="view")
            flat = b._data.reshape((-1,))
            flat = jax.lax.dynamic_update_slice(
                flat, value.reshape((-1,)).astype(b._data.dtype),
                (self._offset,))
            newbase = flat.reshape(b._data.shape)
        b._data = newbase
        b._version += 1
        self._data = value
        self._cache_version = b._version

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod(self._shape)) if self._shape else 1

    @property
    def dtype(self):
        d = self._root()._data
        if d is not None:
            # the root's buffer answers for views too, and works for
            # concrete arrays AND deferred placeholders — metadata
            # queries must not force a bulk flush
            return np.dtype(d.dtype)
        return np.dtype(self._read().dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return invoke(get_op("transpose"), [self], {})

    @property
    def grad(self):
        return self._grad

    # -- conversion --------------------------------------------------------
    def asnumpy(self):
        """Host copy; blocks — the reference's WaitToRead+copy sync point."""
        return np.asarray(self._read())

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def astype(self, dtype, copy=True):
        out = invoke(get_op("Cast"), [self], {"dtype": np.dtype(dtype).name})
        return out

    def copy(self):
        return invoke(get_op("_copy"), [self], {})

    def copyto(self, other):
        """ref: ndarray.py copyto / CopyFromTo (src/ndarray/ndarray.cc)."""
        if isinstance(other, NDArray):
            other._write(self._read().astype(other.dtype))
            return other
        if isinstance(other, Context):
            data = jax.device_put(self._read(), Context(other).jax_device())
            return NDArray(data, ctx=Context(other))
        raise TypeError("copyto does not support type %s" % type(other))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def detach(self):
        """Strip autograd history (ref: ndarray.h:523 Detach)."""
        out = NDArray(self._read(), ctx=self._ctx)
        return out

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """ref: python/mxnet/ndarray/ndarray.py attach_grad → MarkVariables."""
        from .. import autograd
        grad = NDArray(jnp.zeros_like(self._read()), ctx=self._ctx)
        self._grad = grad
        self._grad_req = grad_req
        autograd.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- sync --------------------------------------------------------------
    def wait_to_read(self):
        jax.block_until_ready(self._read())

    def wait_to_write(self):
        jax.block_until_ready(self._read())

    # -- shape manipulation (views) ---------------------------------------
    def reshape(self, *shape, **kwargs):
        """Returns a *view* sharing storage (ref: ndarray.h Reshape)."""
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        from ..ops.tensor import infer_reshape
        new_shape = infer_reshape(self._shape, shape, kwargs.get("reverse", False))
        if int(np.prod(new_shape)) != self.size:
            raise ValueError("cannot reshape %s into %s" % (self._shape, new_shape))
        from .. import autograd
        if autograd.is_recording():
            # under recording, views must be tape ops so gradients flow
            # (the reference records Reshape nodes on the tape too)
            return invoke(get_op("Reshape"), [self], {"shape": tuple(new_shape)})
        root = self._root()
        return NDArray(ctx=self._ctx, base=root, offset=self._offset, shape=new_shape)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        shape = list(self._shape)
        shape.insert(axis if axis >= 0 else axis + self.ndim + 1, 1)
        return self.reshape(tuple(shape))

    def flatten(self):
        return invoke(get_op("Flatten"), [self], {})

    def _view_slice(self, start, stop):
        """Axis-0 contiguous view (ref: NDArray::Slice, ndarray.h:304)."""
        n = self._shape[0]
        start = 0 if start is None else (start + n if start < 0 else start)
        stop = n if stop is None else (stop + n if stop < 0 else min(stop, n))
        if not 0 <= start <= stop <= n:
            raise IndexError("slice [%s:%s) out of range for axis size %d" % (start, stop, n))
        row = int(np.prod(self._shape[1:])) if len(self._shape) > 1 else 1
        root = self._root()
        return NDArray(ctx=self._ctx, base=root,
                       offset=self._offset + start * row,
                       shape=(stop - start,) + self._shape[1:])

    def slice(self, start, stop):
        return self._view_slice(start, stop)

    def at(self, idx):
        """ref: NDArray::At — index into axis 0, drop the axis."""
        v = self._view_slice(idx, idx + 1)
        return v.reshape(self._shape[1:] if len(self._shape) > 1 else (1,))

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        from .. import autograd
        if isinstance(key, int):
            if autograd.is_recording():
                n = self._shape[0]
                k = key + n if key < 0 else key
                out = invoke(get_op("slice_axis"), [self],
                             {"axis": 0, "begin": k, "end": k + 1})
                return invoke(get_op("Reshape"), [out], {"shape": tuple(self._shape[1:]) or (1,)})
            return self.at(key)
        if isinstance(key, slice):
            if key.step is None or key.step == 1:
                if autograd.is_recording():
                    n = self._shape[0]
                    b = 0 if key.start is None else (key.start + n if key.start < 0 else key.start)
                    e = n if key.stop is None else (key.stop + n if key.stop < 0 else min(key.stop, n))
                    return invoke(get_op("slice_axis"), [self],
                                  {"axis": 0, "begin": b, "end": e})
                return self._view_slice(key.start, key.stop)
            return NDArray(self._read()[key], ctx=self._ctx)
        if isinstance(key, NDArray):
            return NDArray(jnp.take(self._read(), key._read().astype(jnp.int32), axis=0),
                           ctx=self._ctx)
        if isinstance(key, (list, np.ndarray)):
            return NDArray(jnp.take(self._read(), jnp.asarray(key, jnp.int32), axis=0),
                           ctx=self._ctx)
        if isinstance(key, tuple):
            # general basic indexing → copy (matches reference semantics for
            # multi-axis indexing)
            key = tuple(k._read().astype(jnp.int32) if isinstance(k, NDArray) else k
                        for k in key)
            return NDArray(self._read()[key], ctx=self._ctx)
        raise TypeError("indexing with %r not supported" % (key,))

    def __setitem__(self, key, value):
        if isinstance(key, slice) and key.start is None and key.stop is None and key.step is None:
            # full-slice store: shape/dtype metadata suffices, so no read
            # of self — a deferred target (or view over one) stays in the
            # open bulk segment and the store records as a program node
            dt = self.dtype
            if isinstance(value, NDArray):
                if value._shape == self._shape and np.dtype(value.dtype) == dt:
                    self._write(value._read_deferred())
                else:
                    self._write(jnp.broadcast_to(value._read().astype(dt),
                                                 self._shape))
            elif isinstance(value, (int, float, bool, np.generic)):
                self._write(jnp.full(self._shape, value, dt))
            else:
                self._write(jnp.broadcast_to(jnp.asarray(value).astype(dt),
                                             self._shape))
            return
        if isinstance(value, NDArray):
            val = value._read()
        elif isinstance(value, (int, float, bool, np.generic)):
            val = None  # fill scalar below
        else:
            val = jnp.asarray(value)
        cur = self._read()
        key2 = key
        if isinstance(key2, NDArray):
            key2 = key2._read().astype(jnp.int32)
        elif isinstance(key2, tuple):
            key2 = tuple(k._read().astype(jnp.int32) if isinstance(k, NDArray) else k
                         for k in key2)
        if val is None:
            new = cur.at[key2].set(value)
        else:
            new = cur.at[key2].set(val.astype(cur.dtype))
        self._write(new)

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        return self._shape[0]

    def __iter__(self):
        for i in range(self._shape[0]):
            yield self[i]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = str(arr)
        except Exception as e:  # pragma: no cover
            body = "<unreadable: %s>" % e
        shape_info = "x".join(str(s) for s in self._shape)
        return "\n%s\n<%s %s @%s>" % (body, type(self).__name__, shape_info, self._ctx)

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(get_op(op_name), [a, b], {})
        if isinstance(other, (int, float, bool, np.generic)):
            return invoke(get_op(scalar_op), [self], {"scalar": float(other)})
        if isinstance(other, np.ndarray):
            o = array(other, ctx=self._ctx)
            a, b = (o, self) if reverse else (self, o)
            return invoke(get_op(op_name), [a, b], {})
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, (int, float, bool, np.generic)):
            return invoke(get_op("_rminus_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar")

    __div__ = __truediv__

    def __rtruediv__(self, o):
        if isinstance(o, (int, float, bool, np.generic)):
            return invoke(get_op("_rdiv_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_div", "_div_scalar", reverse=True)

    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        if isinstance(o, (int, float, bool, np.generic)):
            return invoke(get_op("_rmod_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        if isinstance(o, (int, float, bool, np.generic)):
            return invoke(get_op("_rpower_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_power", "_power_scalar", reverse=True)

    def __neg__(self):
        return invoke(get_op("negative"), [self], {})

    def __abs__(self):
        return invoke(get_op("abs"), [self], {})

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    def _inplace(self, other, op_name, scalar_op):
        res = self._binop(other, op_name, scalar_op)
        if res._shape == self._shape \
                and np.dtype(res.dtype) == np.dtype(self.dtype):
            # may hand a _Pending to _write: the read-modify-write stays
            # inside the open bulk segment (views write through as a
            # recorded scatter node)
            self._write(res._read_deferred())
        else:
            self._write(res._read().astype(self.dtype))
        return self

    def __iadd__(self, o):
        return self._inplace(o, "broadcast_add", "_plus_scalar")

    def __isub__(self, o):
        return self._inplace(o, "broadcast_sub", "_minus_scalar")

    def __imul__(self, o):
        return self._inplace(o, "broadcast_mul", "_mul_scalar")

    def __itruediv__(self, o):
        return self._inplace(o, "broadcast_div", "_div_scalar")

    __idiv__ = __itruediv__

    # convenience methods mirroring the reference's method surface
    def sum(self, *args, **kwargs):
        return _call("sum", self, *args, **kwargs)

    def mean(self, *args, **kwargs):
        return _call("mean", self, *args, **kwargs)

    def max(self, *args, **kwargs):
        return _call("max", self, *args, **kwargs)

    def min(self, *args, **kwargs):
        return _call("min", self, *args, **kwargs)

    def argmax(self, *args, **kwargs):
        return _call("argmax", self, *args, **kwargs)

    def argmin(self, *args, **kwargs):
        return _call("argmin", self, *args, **kwargs)

    def abs(self):
        return invoke(get_op("abs"), [self], {})

    def square(self):
        return invoke(get_op("square"), [self], {})

    def sqrt(self):
        return invoke(get_op("sqrt"), [self], {})

    def exp(self):
        return invoke(get_op("exp"), [self], {})

    def log(self):
        return invoke(get_op("log"), [self], {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke(get_op("transpose"), [self], {"axes": axes})

    def clip(self, a_min, a_max):
        return invoke(get_op("clip"), [self], {"a_min": a_min, "a_max": a_max})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse
        return sparse.cast_storage(self, stype)

    def as_nd_ndarray(self):
        return self


def _call(name, *args, **kwargs):
    from . import register as _reg
    return getattr(_reg.module_surface, name)(*args, **kwargs)


_ENGINE = None


def _engine_mod():
    global _ENGINE
    if _ENGINE is None:
        from .. import engine
        _ENGINE = engine
    return _ENGINE


# ---------------------------------------------------------------------------
# eager op invocation (the imperative runtime; ref: src/imperative/imperative.cc)
# ---------------------------------------------------------------------------

def invoke(op: Operator, inputs, params, out=None):
    """Eager dispatch of one operator — Imperative::Invoke reborn.

    inputs: list[NDArray]; params: dict of static attributes.
    Handles: jit-cached dispatch, PRNG key supply, autograd tape recording
    (jax.vjp), aux-output write-back for mutating ops, `out=` stores.
    """
    from .. import autograd

    params = {k: v for k, v in params.items() if v is not None or k in ("axis",)}
    ctx_override = params.pop("ctx", None)
    params.pop("name", None)
    is_train = autograd.is_training()
    recording = autograd.is_recording() and op.differentiable

    # engine bulking (threaded_engine.h BulkAppend reborn): inside a
    # `with mx.engine.bulk()` scope, pure eager ops are recorded and later
    # replayed as ONE jitted program instead of dispatched one by one
    kw = {}
    if op.needs_rng:
        kw["rng"] = random_state.next_key()

    _eng = _engine_mod()
    if (_eng._current() is not None
            and ctx_override is None
            and not _NAIVE_ENGINE and not getattr(op, "no_jit", False)
            and not (out is not None and recording)):
        # ``out=`` stores and mutating ops (optimizer updates) are
        # deferrable too (round 5 — the reference bulks optimizer updates
        # inside train segments, threaded_engine.h:472-509): the write
        # plan below rebinds each target's buffer to its pending output
        # at record time, so downstream deferred ops chain through the
        # updated value and the whole train step flushes as ONE program.
        # Requirements: non-view plain-dense targets and exact
        # shape/dtype match (checked via out_reqs before recording —
        # the eager path's astype/write-through fixups don't apply to a
        # buffer rebind).
        write_plan = None       # [(output slot, target NDArray)]
        deferrable = True
        if out is not None:
            touts = [out] if isinstance(out, NDArray) else list(out)
            if op.mutate_inputs:
                write_plan = [(0, touts[0])] + [
                    (j + 1, inputs[idx])
                    for j, idx in enumerate(op.mutate_inputs[1:])]
            elif op.fvisible is None and len(touts) <= op.num_visible_outputs:
                # visible outputs come first, so target i <- output i
                write_plan = list(enumerate(touts))
            else:
                deferrable = False  # dynamic visibility: eager fixups apply
            deferrable = deferrable and all(
                type(t) is NDArray and t._base is None
                for _, t in (write_plan or ()))
        if deferrable:
            vals = [a._read_deferred() for a in inputs]
            out_reqs = None if write_plan is None else [
                (slot, t._shape, str(np.dtype(t.dtype)))
                for slot, t in write_plan]
            pend = _eng.maybe_defer(op, params, vals, is_train, kw,
                                    rec=recording, nd_inputs=inputs,
                                    out_reqs=out_reqs)
            if pend is not None:
                if write_plan is not None:
                    for slot, t in write_plan:
                        t._write(pend[slot])   # registers t as owner
                    return touts[0] if len(touts) == 1 else touts
                ctx = inputs[0]._ctx if inputs else current_context()
                out_arrays = []
                for p in pend:
                    nd_out = NDArray(p, ctx=ctx)
                    p.owners.append(weakref.ref(nd_out))
                    out_arrays.append(nd_out)
                n_vis = op.visible_outputs(params, len(out_arrays))
                visible = out_arrays[:n_vis]
                return visible[0] if len(visible) == 1 else visible

    vals = [a._read() for a in inputs]

    from .. import profiler as _profiler
    # async dispatch: the span is dispatch time unless sync mode blocks
    # until ready inside it — the event says which (graftscope satellite:
    # op durations must never masquerade as device latency)
    _span = _profiler.op_span(op.name, "imperative",
                              args={"device_time": _profiler.want_sync()})
    if _span is not None:
        _span.__enter__()
    _pulse = _lens.pulse_active()
    _t_dispatch = None
    try:
        if recording:
            fn = op.bind(params, is_train)
            if kw:
                rng = kw["rng"]
                wrapped = lambda *xs: fn(*xs, rng=rng)
            else:
                wrapped = fn
            # jax.vjp interleaves host linearization tracing with the
            # execution — no clean dispatch instant exists, so the
            # device ledger books only the residual wait below (an
            # undercount, never host tracing booked as device time)
            out_vals, vjp_fn = jax.vjp(wrapped, *vals)
        else:
            fn = op.bind(params, is_train)
            if _span is not None or _pulse:
                _t_dispatch = _time.perf_counter()  # after bind: the
                #                                     executing call only
            out_vals = fn(*vals, **kw)
            vjp_fn = None
    except Exception as exc:
        # close the span on the exception path too: a crash-time trace
        # must not lose the op that raised (graftwatch satellite)
        if _span is not None:
            _span.__exit__(type(exc), exc, None)
        raise
    _sync_booked = False
    if _span is not None:
        if _profiler.want_sync():
            # device-time lens: under sync mode dispatch→ready IS this
            # op's device latency — same ledger the sync-mode bulk
            # flushes feed, so eager (unbulked) steps decompose too.
            # Recorded ops book the blocking wait only (_t_dispatch is
            # None there); cache-miss calls still include jit compile
            _sync_booked = True
            _t_block = _time.perf_counter()
            jax.block_until_ready(out_vals)
            _lens.device(_t_dispatch if _t_dispatch is not None
                         else _t_block, _time.perf_counter())
        _span.__exit__()
    if _pulse and not _sync_booked:
        # graftpulse: async eager dispatch — hand the results to the
        # reaper so dispatch→device-done books into this thread's device
        # ledger without blocking here.  Recorded ops carry no clean
        # dispatch instant (host tracing above): the post-call instant
        # starts their span — an undercount, never host work booked as
        # device time.  The sync path above books directly and skips
        # the enqueue (no-double-booking contract).
        _lens.device_async(out_vals, _t_dispatch if _t_dispatch is not None
                           else _time.perf_counter())
    if _NAIVE_ENGINE:
        jax.block_until_ready(out_vals)
    first = out_vals[0] if isinstance(out_vals, tuple) else out_vals
    devs = getattr(first, "devices", None)
    if devs is not None:
        try:
            _DISPATCH_DEVICES.update(devs())
        except Exception:       # tracers inside jit have no devices
            pass

    if not isinstance(out_vals, tuple):
        out_vals = (out_vals,)

    if ctx_override is not None:
        ctx = Context(ctx_override)
        dev = ctx.jax_device()
        out_vals = tuple(jax.device_put(v, dev) for v in out_vals)
    else:
        ctx = inputs[0]._ctx if inputs else current_context()
    out_arrays = [NDArray(v, ctx=ctx) for v in out_vals]

    if recording:
        autograd._record(op, list(inputs), out_arrays, vjp_fn, fn=wrapped)

    n_visible = op.visible_outputs(params, len(out_arrays))

    # mutating ops (optimizer updates): write hidden state outputs back into
    # the declared mutable inputs (ref: optimizer ops write their state in
    # place via kWriteInplace)
    if out is not None and op.mutate_inputs:
        targets = [out] if isinstance(out, NDArray) else list(out)
        targets[0]._write(out_vals[0].astype(targets[0].dtype))
        for extra_val, in_idx in zip(out_vals[1:], op.mutate_inputs[1:]):
            inputs[in_idx]._write(extra_val.astype(inputs[in_idx].dtype))
        return targets[0] if len(targets) == 1 else targets
    if out is not None:
        targets = [out] if isinstance(out, NDArray) else list(out)
        for t, v in zip(targets, out_vals[:n_visible]):
            t._write(v.astype(t.dtype))
        return targets[0] if len(targets) == 1 else targets

    visible = out_arrays[:n_visible]
    if len(visible) == 1:
        return visible[0]
    return visible


def imperative_invoke(op_name, *inputs, out=None, **params):
    """String-name invoke (parity with MXImperativeInvoke, c_api_ndarray.cc:117)."""
    return invoke(get_op(op_name), list(inputs), params, out=out)


# ---------------------------------------------------------------------------
# creation & utilities (parity: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    """ref: python/mxnet/ndarray/utils.py array"""
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
        if dtype is None:
            dtype = src.dtype
    elif isinstance(source_array, np.ndarray):
        src = source_array
        if dtype is None:
            dtype = _default_dtype_for(src)
    else:
        # python lists/scalars default to float32, like the reference
        # (python/mxnet/ndarray/utils.py array)
        src = np.asarray(source_array)
        if dtype is None:
            dtype = np.float32 if src.dtype.kind in "fiub" else src.dtype
    src = src.astype(dtype, copy=False)
    ctx = ctx if ctx is not None else current_context()
    # device_put straight from host memory: jnp.asarray first would bounce
    # the buffer through the DEFAULT device (an accelerator upload + a
    # download when ctx is cpu — measured in seconds through the TPU
    # tunnel for data-pipeline batches)
    data = jax.device_put(np.ascontiguousarray(src), ctx.jax_device())
    return NDArray(data, ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    ctx = ctx if ctx is not None else current_context()
    if isinstance(shape, int):
        shape = (shape,)
    # Allocate directly ON the target device.  jnp.zeros would materialize
    # on the default device first and device_put would then bounce the
    # buffer through the host — for a cpu-ctx scratch array (parameter
    # init) that is an accelerator->host download of the full tensor per
    # call, measured in minutes for ~1B params over the axon tunnel.
    with jax.default_device(ctx.jax_device()):
        data = jnp.zeros(shape, jnp.dtype(dtype))
    return NDArray(data, ctx=ctx)


def waitall():
    """Block until all outstanding work has executed
    (ref: mx.nd.waitall → Engine::WaitForAll, threaded_engine.cc).

    Enqueues a barrier computation on every device that has seen
    dispatches and blocks on it — in-order execution per stream makes
    that equivalent to draining the queues, without pinning any user
    buffer."""
    for d in list(_DISPATCH_DEVICES):
        try:
            token = jax.device_put(jnp.zeros((), jnp.float32), d)
            jax.block_until_ready(_WAITALL_BARRIER(token))
        except Exception:           # device gone / backend quirk
            pass
    _DISPATCH_DEVICES.clear()


@jax.jit
def _WAITALL_BARRIER(t):
    # compiled once; executes after everything queued before it per stream
    return t + 1


def concatenate(arrays, axis=0, always_copy=True):
    return invoke(get_op("Concat"), list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return invoke(get_op("transpose"), [tensor], {"axes": tuple(axes)})
