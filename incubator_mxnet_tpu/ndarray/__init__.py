"""`nd` namespace: NDArray + one generated function per registered operator.

Parity surface: python/mxnet/ndarray/__init__.py + ndarray.py + utils.py.
"""
from __future__ import annotations

import sys

import numpy as np

from ..context import Context, current_context, cpu
from ..ops.registry import get_op
from .ndarray import (NDArray, array, empty, invoke, waitall, concatenate,
                      moveaxis, imperative_invoke)
from . import register as _register
from . import ndarray as _ndarray_mod


# -- explicit creation wrappers (pythonic signatures over the raw ops) ------

def zeros(shape, ctx=None, dtype="float32", stype=None, **kwargs):
    """ref: python/mxnet/ndarray/utils.py zeros"""
    if isinstance(shape, int):
        shape = (shape,)
    if stype not in (None, "default"):
        from . import sparse
        return sparse.zeros(stype, shape, ctx=ctx, dtype=dtype)
    return invoke(get_op("_zeros"), [], {"shape": tuple(shape), "dtype": np.dtype(dtype).name,
                                         "ctx": ctx})


def ones(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return invoke(get_op("_ones"), [], {"shape": tuple(shape), "dtype": np.dtype(dtype).name,
                                        "ctx": ctx})


def full(shape, val, ctx=None, dtype="float32", out=None):
    if isinstance(shape, int):
        shape = (shape,)
    return invoke(get_op("_full"), [], {"shape": tuple(shape), "value": float(val),
                                        "dtype": np.dtype(dtype).name, "ctx": ctx}, out=out)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    return invoke(get_op("_arange"), [], {"start": start, "stop": stop, "step": step,
                                          "repeat": repeat, "dtype": np.dtype(dtype).name,
                                          "ctx": ctx})


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return invoke(get_op("_eye"), [], {"N": N, "M": M, "k": k,
                                       "dtype": np.dtype(dtype).name, "ctx": ctx})


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return invoke(get_op("_linspace"), [], {"start": start, "stop": stop, "num": num,
                                            "endpoint": endpoint,
                                            "dtype": np.dtype(dtype).name, "ctx": ctx})


def zeros_like(data, **kwargs):
    return invoke(get_op("zeros_like"), [data], {})


def ones_like(data, **kwargs):
    return invoke(get_op("ones_like"), [data], {})


def save(fname, data):
    """Save NDArrays (ref: NDArray::Save, src/ndarray/ndarray.cc) — .npz based."""
    from .utils import save as _save
    return _save(fname, data)


def load(fname):
    from .utils import load as _load
    return _load(fname)


def load_buffer(buf):
    """In-memory .params parse (ref: MXNDArrayLoadFromBuffer) — the
    loader the C predict surface and the serving registry share."""
    from .utils import load_buffer as _load_buffer
    return _load_buffer(buf)


def onehot_encode(indices, out):
    """legacy helper (ref: python/mxnet/ndarray/ndarray.py onehot_encode)."""
    depth = out.shape[1]
    res = invoke(get_op("one_hot"), [indices], {"depth": depth})
    out._write(res._read().astype(out.dtype))
    return out


# auto-generate the remaining op surface
_register.populate(globals())
_register.module_surface = sys.modules[__name__]


def Custom(*args, **kwargs):
    """Python-defined custom op (ref: src/operator/custom/custom.cc;
    register via mx.operator.register)."""
    from ..operator import custom_nd
    return custom_nd(*args, **kwargs)


def cast_storage(arr, stype="default"):
    """Storage-type cast honoring sparse stypes on the eager surface
    (ref: src/operator/tensor/cast_storage.cc).  Shadows the registry's
    dense pass-through (which serves compiled Symbol graphs where every
    tensor is dense)."""
    from . import sparse as _sparse
    return _sparse.cast_storage(arr, stype)


def sparse_retain(data, indices):
    """Row retention preserving row_sparse storage on the eager surface
    (ref: src/operator/tensor/sparse_retain.cc)."""
    from . import sparse as _sparse
    from .sparse import RowSparseNDArray
    if isinstance(data, RowSparseNDArray):
        return _sparse.retain(data, indices)
    return invoke(get_op("_sparse_retain_dense"), [data, indices], {})

# expose submodule-style accessors for parity: nd.random, nd.linalg
from . import random  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import contrib  # noqa: E402,F401

NDArray = NDArray  # re-export for clarity
