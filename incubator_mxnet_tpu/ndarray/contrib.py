"""``nd.contrib`` namespace — short names over the ``_contrib_*`` ops.

Parity: python/mxnet/ndarray/contrib.py (code-gen'd from the ``_contrib_``
prefix in the reference).
"""
from __future__ import annotations

from ..ops.registry import _REGISTRY
from .register import make_op_func

__all__ = []
for _name, _op in list(_REGISTRY.items()):
    if _name.startswith("_contrib_"):
        _short = _name[len("_contrib_"):]
        globals()[_short] = make_op_func(_short, _op)
        __all__.append(_short)
