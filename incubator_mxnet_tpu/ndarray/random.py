"""nd.random namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

import numpy as np

from ..ops.registry import get_op
from .ndarray import NDArray, invoke


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _simple(op_name, params, shape, dtype, ctx, out):
    params = dict(params)
    params["shape"] = _shape(shape)
    params["dtype"] = np.dtype(dtype if dtype not in (None, "None") else "float32").name
    params["ctx"] = ctx
    return invoke(get_op(op_name), [], params, out=out)


def uniform(low=0, high=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    if isinstance(low, NDArray):
        return invoke(get_op("_sample_uniform"), [low, high], {"shape": _shape(shape)}, out=out)
    return _simple("_random_uniform", {"low": float(low), "high": float(high)},
                   shape, dtype, ctx, out)


def normal(loc=0, scale=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    if isinstance(loc, NDArray):
        return invoke(get_op("_sample_normal"), [loc, scale], {"shape": _shape(shape)}, out=out)
    return _simple("_random_normal", {"loc": float(loc), "scale": float(scale)},
                   shape, dtype, ctx, out)


def gamma(alpha=1, beta=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    return _simple("_random_gamma", {"alpha": float(alpha), "beta": float(beta)},
                   shape, dtype, ctx, out)


def exponential(lam=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    return _simple("_random_exponential", {"lam": float(lam)}, shape, dtype, ctx, out)


def poisson(lam=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    return _simple("_random_poisson", {"lam": float(lam)}, shape, dtype, ctx, out)


def negative_binomial(k=1, p=1, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    return _simple("_random_negative_binomial", {"k": int(k), "p": float(p)},
                   shape, dtype, ctx, out)


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype=None, ctx=None,
                                  out=None, **kwargs):
    return _simple("_random_generalized_negative_binomial",
                   {"mu": float(mu), "alpha": float(alpha)}, shape, dtype, ctx, out)


def multinomial(data, shape=None, get_prob=False, out=None, dtype="int32", **kwargs):
    return invoke(get_op("_sample_multinomial"), [data],
                  {"shape": _shape(shape), "get_prob": get_prob, "dtype": dtype}, out=out)


def shuffle(data, **kwargs):
    return invoke(get_op("_shuffle"), [data], {})
