"""NDArray save/load (ref: src/ndarray/ndarray.cc NDArray::Save/Load,
python/mxnet/ndarray/utils.py save/load).

Format: numpy .npz with a manifest — functionally equivalent to the
reference's dmlc::Stream binary container (named or unnamed array lists,
sparse-aware).  Files written by this module round-trip dense and sparse
arrays with names preserved.
"""
from __future__ import annotations

import json

import numpy as np

__all__ = ["save", "load"]

_MAGIC = "mxtpu-ndarray-v1"


def save(fname, data):
    from .ndarray import NDArray
    from .sparse import RowSparseNDArray, CSRNDArray

    if isinstance(data, NDArray):
        data = [data]
    payload = {}
    manifest = {"magic": _MAGIC, "entries": []}
    if isinstance(data, dict):
        items = list(data.items())
    else:
        items = [(None, v) for v in data]
    for i, (name, arr) in enumerate(items):
        ent = {"name": name, "idx": i}
        if isinstance(arr, RowSparseNDArray):
            ent["stype"] = "row_sparse"
            ent["shape"] = list(arr.shape)
            payload["a%d_data" % i] = arr.data.asnumpy()
            payload["a%d_indices" % i] = arr.indices.asnumpy()
        elif isinstance(arr, CSRNDArray):
            ent["stype"] = "csr"
            ent["shape"] = list(arr.shape)
            payload["a%d_data" % i] = arr.data.asnumpy()
            payload["a%d_indices" % i] = arr.indices.asnumpy()
            payload["a%d_indptr" % i] = arr.indptr.asnumpy()
        else:
            ent["stype"] = "default"
            payload["a%d_data" % i] = arr.asnumpy()
        manifest["entries"].append(ent)
    payload["__manifest__"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    with open(fname, "wb") as f:
        np.savez(f, **payload)


def load(fname):
    from .ndarray import array
    from . import sparse

    with np.load(fname) as z:
        manifest = json.loads(bytes(z["__manifest__"].tobytes()).decode())
        if manifest.get("magic") != _MAGIC:
            raise ValueError("not a %s file" % _MAGIC)
        named = any(e["name"] for e in manifest["entries"])
        out_list, out_dict = [], {}
        for e in manifest["entries"]:
            i = e["idx"]
            if e["stype"] == "row_sparse":
                arr = sparse.row_sparse_array(
                    (z["a%d_data" % i], z["a%d_indices" % i]), shape=tuple(e["shape"]))
            elif e["stype"] == "csr":
                arr = sparse.csr_matrix(
                    (z["a%d_data" % i], z["a%d_indices" % i], z["a%d_indptr" % i]),
                    shape=tuple(e["shape"]))
            else:
                arr = array(z["a%d_data" % i])
            if named:
                out_dict[e["name"]] = arr
            else:
                out_list.append(arr)
    return out_dict if named else out_list
