"""NDArray save/load in the MXNet binary container format.

Byte-compatible with the reference (src/ndarray/ndarray.cc
NDArray::Save/Load + the list container written by MXNDArraySave,
src/c_api/c_api.cc): ``.params`` files written here load in stock MXNet
and vice versa — including sparse arrays and the V1/legacy dense
formats on read.  Files from this module's earlier private .npz format
are still recognized and loaded.

Layout (little-endian):
  uint64 0x112 (kMXAPINDArrayListMagic), uint64 reserved
  uint64 n_arrays, then per array NDArray::Save:
      uint32 0xF993fac9 (V2 magic), int32 stype,
      [storage_shape if sparse], shape, int32 dev_type, int32 dev_id,
      int32 dtype flag, [aux dtypes+shapes], raw data, [raw aux data]
  uint64 n_names, then per name: uint64 len + bytes
Shapes are uint32 ndim + int64[ndim] (nnvm::Tuple::Save).
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = ["save", "load", "load_buffer"]

_LIST_MAGIC = 0x112
_NDARRAY_V2_MAGIC = 0xF993FAC9
_NDARRAY_V1_MAGIC = 0xF993FAC8

# mshadow type flags (mshadow/base.h)
_FLAG_OF = {np.dtype("float32"): 0, np.dtype("float64"): 1,
            np.dtype("float16"): 2, np.dtype("uint8"): 3,
            np.dtype("int32"): 4, np.dtype("int8"): 5,
            np.dtype("int64"): 6}
_DTYPE_OF = {v: k for k, v in _FLAG_OF.items()}

_STYPE_DEFAULT, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2


def _write_shape(out, shape):
    out.append(struct.pack("<I", len(shape)))
    out.append(struct.pack("<%dq" % len(shape), *shape))


def _dtype_flag(dtype):
    dtype = np.dtype(dtype)
    if dtype not in _FLAG_OF:
        raise ValueError("dtype %s not representable in the MXNet binary "
                         "format (bfloat16 et al.: cast to float32 first)"
                         % dtype)
    return _FLAG_OF[dtype]


def _widen_if_needed(a):
    """MXNet 1.x has no container flag for bf16 etc.: widen to f32 with a
    warning so save never silently fails NOR silently alters data."""
    if a.dtype in _FLAG_OF:
        return a
    import warnings
    warnings.warn("dtype %s has no MXNet 1.x .params representation; "
                  "saving as float32 (loads back as float32)" % a.dtype,
                  stacklevel=4)
    return a.astype(np.float32)


def _save_one(out, arr):
    from .sparse import RowSparseNDArray, CSRNDArray
    out.append(struct.pack("<I", _NDARRAY_V2_MAGIC))
    if isinstance(arr, RowSparseNDArray):
        data = np.ascontiguousarray(_widen_if_needed(arr.data.asnumpy()))
        aux = [np.ascontiguousarray(arr.indices.asnumpy().astype(np.int64))]
        out.append(struct.pack("<i", _STYPE_ROW_SPARSE))
        _write_shape(out, data.shape)          # storage shape
    elif isinstance(arr, CSRNDArray):
        data = np.ascontiguousarray(_widen_if_needed(arr.data.asnumpy()))
        # aux order kIndPtr, kIdx (include/mxnet/ndarray.h csr enum)
        aux = [np.ascontiguousarray(arr.indptr.asnumpy().astype(np.int64)),
               np.ascontiguousarray(arr.indices.asnumpy().astype(np.int64))]
        out.append(struct.pack("<i", _STYPE_CSR))
        _write_shape(out, data.shape)
    else:
        a = _widen_if_needed(arr.asnumpy())
        if a.ndim == 0:
            # MXNet 1.x has no 0-d arrays (ndim 0 encodes "empty"); the
            # value survives as shape (1,)
            a = a.reshape(1)
        data = np.ascontiguousarray(a)
        aux = []
        out.append(struct.pack("<i", _STYPE_DEFAULT))
    _write_shape(out, data.shape if not aux else arr.shape)
    out.append(struct.pack("<ii", 1, 0))       # Context: kCPU, dev_id 0
    out.append(struct.pack("<i", _dtype_flag(data.dtype)))
    for a in aux:
        out.append(struct.pack("<i", _dtype_flag(a.dtype)))
        _write_shape(out, a.shape)
    out.append(data.tobytes())
    for a in aux:
        out.append(a.tobytes())


def save(fname, data):
    """Write arrays (list or name→array dict) as a .params file
    (ref: python/mxnet/ndarray/utils.py save → MXNDArraySave)."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    out = [struct.pack("<QQ", _LIST_MAGIC, 0),
           struct.pack("<Q", len(arrays))]
    for arr in arrays:
        _save_one(out, arr)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    # atomic publish (graftarmor): write-to-tmp + rename, so a crash or
    # a concurrent reader mid-save can never observe a truncated
    # .params file — the name either maps to the old bytes or the new
    import os
    tmp = "%s.tmp.%d" % (fname, os.getpid())
    try:
        with open(tmp, "wb") as f:
            f.write(b"".join(out))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, fname)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, fmt):
        size = struct.calcsize(fmt)
        vals = struct.unpack_from(fmt, self.buf, self.pos)
        self.pos += size
        return vals if len(vals) > 1 else vals[0]

    def read_bytes(self, n):
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def read_shape(self):
        ndim = self.read("<I")
        if ndim == 0:
            return ()
        return tuple(self.read("<%dq" % ndim)) if ndim > 1 \
            else (self.read("<q"),)


def _load_one(r):
    from .ndarray import array
    from . import sparse
    magic = r.read("<I")
    if magic != _NDARRAY_V2_MAGIC:
        # V1 / legacy dense format (ref: NDArray::LegacyLoad)
        if magic == _NDARRAY_V1_MAGIC:
            shape = r.read_shape()
        else:
            # pre-V1: the "magic" is ndim, dims are uint32
            ndim = magic
            shape = tuple(r.read("<%dI" % ndim)) if ndim > 1 \
                else ((r.read("<I"),) if ndim else ())
        if not shape:
            return array(np.zeros((0,), np.float32))
        r.read("<ii")                      # context
        flag = r.read("<i")
        dtype = _DTYPE_OF[flag]
        n = int(np.prod(shape))
        data = np.frombuffer(r.read_bytes(n * dtype.itemsize),
                             dtype=dtype).reshape(shape)
        return array(data)

    stype = r.read("<i")
    nad = {_STYPE_DEFAULT: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}[stype]
    sshape = r.read_shape() if nad > 0 else None
    shape = r.read_shape()
    if not shape:
        # ndim 0 = empty NDArray: nothing else on the stream
        # (ref: NDArray::Save early return after shape for is_none())
        return array(np.zeros((0,), np.float32))
    r.read("<ii")                          # context
    flag = r.read("<i")
    dtype = _DTYPE_OF[flag]
    aux_specs = []
    for _ in range(nad):
        aflag = r.read("<i")
        ashape = r.read_shape()
        aux_specs.append((_DTYPE_OF[aflag], ashape))
    dshape = sshape if nad > 0 else shape
    n = int(np.prod(dshape)) if dshape else 0
    data = np.frombuffer(r.read_bytes(n * dtype.itemsize),
                         dtype=dtype).reshape(dshape)
    aux = []
    for adtype, ashape in aux_specs:
        cnt = int(np.prod(ashape)) if ashape else 0
        aux.append(np.frombuffer(r.read_bytes(cnt * adtype.itemsize),
                                 dtype=adtype).reshape(ashape))
    if stype == _STYPE_ROW_SPARSE:
        return sparse.row_sparse_array((data, aux[0]), shape=shape)
    if stype == _STYPE_CSR:
        indptr, indices = aux
        return sparse.csr_matrix((data, indices, indptr), shape=shape)
    return array(data)


def _load_legacy_npz(fname):
    """Reader for this module's earlier private .npz container."""
    import json
    from .ndarray import array
    from . import sparse
    with np.load(fname) as z:
        manifest = json.loads(bytes(z["__manifest__"].tobytes()).decode())
        named = any(e["name"] for e in manifest["entries"])
        out_list, out_dict = [], {}
        for e in manifest["entries"]:
            i = e["idx"]
            if e["stype"] == "row_sparse":
                arr = sparse.row_sparse_array(
                    (z["a%d_data" % i], z["a%d_indices" % i]),
                    shape=tuple(e["shape"]))
            elif e["stype"] == "csr":
                arr = sparse.csr_matrix(
                    (z["a%d_data" % i], z["a%d_indices" % i],
                     z["a%d_indptr" % i]), shape=tuple(e["shape"]))
            else:
                arr = array(z["a%d_data" % i])
            if named:
                out_dict[e["name"]] = arr
            else:
                out_list.append(arr)
    return out_dict if named else out_list


def load_buffer(buf):
    """Load a .params payload straight from ``bytes`` — the in-memory
    twin of :func:`load` (ref: MXNDArrayLoadFromBuffer,
    src/c_api/c_api.cc).  The C predict surface hands param bytes over
    the ABI and the serving registry receives them from model stores;
    neither should round-trip through a temp file just to parse a
    buffer this module wrote in the first place."""
    if bytes(buf[:2]) == b"PK":            # zip → legacy npz container
        import tempfile
        # np.load needs a seekable file; spool without touching disk
        with tempfile.SpooledTemporaryFile(max_size=1 << 30) as f:
            f.write(buf)
            f.seek(0)
            return _load_legacy_npz(f)
    r = _Reader(buf)
    magic, _reserved = r.read("<QQ")
    if magic != _LIST_MAGIC:
        raise ValueError("not an MXNet NDArray buffer (bad magic 0x%x)"
                         % magic)
    n = r.read("<Q")
    arrays = [_load_one(r) for _ in range(n)]
    n_names = r.read("<Q")
    names = [r.read_bytes(r.read("<Q")).decode() for _ in range(n_names)]
    if names:
        return dict(zip(names, arrays))
    return arrays


def load(fname):
    """Load a .params file (MXNet binary; legacy npz sniffed by header)
    (ref: python/mxnet/ndarray/utils.py load → MXNDArrayLoad)."""
    with open(fname, "rb") as f:
        head = f.read(8)
        if head[:2] == b"PK":              # zip → legacy npz container
            return _load_legacy_npz(fname)
        buf = head + f.read()
    return load_buffer(buf)
