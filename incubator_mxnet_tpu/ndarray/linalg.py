"""``nd.linalg`` namespace — short names over the ``_linalg_*`` op family.

Parity: python/mxnet/ndarray/linalg.py (the reference code-gens these from
the ``_linalg_`` prefix; we do the same over the in-process registry).
"""
from __future__ import annotations

from ..ops.registry import get_op
from .register import make_op_func

_OPS = ("gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "sumlogdiag",
        "syrk", "gelqf", "syevd")

for _n in _OPS:
    globals()[_n] = make_op_func(_n, get_op("_linalg_" + _n))

__all__ = list(_OPS)
