"""Auto-generation of the ``nd.<op>`` function surface.

Parity with python/mxnet/ndarray/register.py:29 — the reference code-gens one
Python function per registered operator at import time by querying the C API;
we do the same over the in-process registry.  Each generated function splits
NDArray arguments from attribute kwargs and funnels into
``ndarray.invoke`` (the imperative runtime).
"""
from __future__ import annotations

import keyword

from ..ops.registry import _REGISTRY, Operator
from .ndarray import NDArray, invoke

module_surface = None  # set by ndarray/__init__ (used for method dispatch)


def make_op_func(op_name: str, op: Operator):
    def generic_op(*args, out=None, name=None, **kwargs):
        arrays = []
        rest = list(args)
        while rest and isinstance(rest[0], NDArray):
            arrays.append(rest.pop(0))
        if rest:
            # allow trailing scalars for ops like slice_axis(data, axis, b, e)?
            raise TypeError(
                "%s: positional arguments after NDArrays must be keyword "
                "attributes, got %r" % (op_name, rest))
        if op.input_names:
            for n in op.input_names:
                v = kwargs.pop(n, None)
                if isinstance(v, NDArray):
                    arrays.append(v)
        else:
            for k in list(kwargs):
                if isinstance(kwargs[k], NDArray):
                    arrays.append(kwargs.pop(k))
        return invoke(op, arrays, kwargs, out=out)

    generic_op.__name__ = op_name
    generic_op.__qualname__ = op_name
    generic_op.__doc__ = (op.doc or "") + "\n\n(auto-generated from op registry; " \
        "parity: python/mxnet/ndarray/register.py codegen)"
    return generic_op


def populate(namespace: dict):
    for name, op in list(_REGISTRY.items()):
        if keyword.iskeyword(name) or not name.replace("_", "a").isidentifier():
            continue
        if name in namespace:
            continue
        namespace[name] = make_op_func(name, op)
