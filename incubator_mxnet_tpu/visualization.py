"""Network visualization: text summaries and graphviz rendering.

TPU-native rebirth of python/mxnet/visualization.py (print_summary:47,
plot_network:196).  Both walk our Symbol graph directly instead of the
JSON round-trip; parameter counts come from the inferred shapes of each
node's variable inputs, so they are exact for every op (the reference
hand-codes the arithmetic for Conv/FC/BatchNorm only).
"""
from __future__ import annotations

import numpy as np

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def _node_param_info(symbol, shape):
    """Per-op-node (out_shape, n_params, predecessors) via one shape pass."""
    internals = symbol.get_internals()
    shape_of = {}
    var_shape = {}
    if shape is not None:
        # one propagation covers both layer outputs and variable shapes
        arg_shapes, out_shapes, aux_shapes = internals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_of = dict(zip(internals.list_outputs(), out_shapes))
        var_shape = dict(zip(internals.list_arguments(), arg_shapes))
        var_shape.update(zip(internals.list_auxiliary_states(), aux_shapes))
    rows = []
    for node in symbol._topo():
        if node.is_variable():
            continue
        n_params = 0
        preds = []
        for i in node._inputs:
            b = i._base()
            if b.is_variable():
                if b.name in var_shape and b.name != "data" \
                        and not b.name.endswith(("label",)):
                    n_params += int(np.prod(var_shape[b.name] or (0,)))
            else:
                preds.append(b._name)
        key = (node._name or "") + "_output"
        out_shape = shape_of.get(key, ())
        rows.append((node, out_shape, n_params, preds))
    return rows


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Print a layer table: name(type), output shape, #params, inputs.

    ref: visualization.py print_summary:47 (same table layout).
    """
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    pos = [int(line_length * p) if p <= 1 else int(p) for p in positions]

    def fmt_row(fields):
        line = ""
        for f, p in zip(fields, pos):
            line = (line + str(f))[:p]
            line += " " * (p - len(line))
        return line

    lines = ["_" * line_length,
             fmt_row(["Layer (type)", "Output Shape", "Param #",
                      "Previous Layer"]),
             "=" * line_length]
    total = 0
    rows = _node_param_info(symbol, shape)
    for k, (node, out_shape, n_params, preds) in enumerate(rows):
        total += n_params
        lines.append(fmt_row(
            ["%s(%s)" % (node._name, node._op.name),
             "x".join(str(x) for x in (out_shape[1:] if out_shape else ())),
             n_params, preds[0] if preds else ""]))
        for extra in preds[1:]:
            lines.append(fmt_row(["", "", "", extra]))
        lines.append(("=" if k == len(rows) - 1 else "_") * line_length)
    lines.append("Total params: %d" % total)
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


_NODE_STYLE = {
    "Convolution": ("#fb8072", "box"),
    "Deconvolution": ("#fb8072", "box"),
    "FullyConnected": ("#fb8072", "box"),
    "BatchNorm": ("#bebada", "box"),
    "Activation": ("#ffffb3", "box"),
    "LeakyReLU": ("#ffffb3", "box"),
    "Pooling": ("#80b1d3", "box"),
    "Concat": ("#fdb462", "box"),
    "Flatten": ("#fdb462", "box"),
    "Reshape": ("#fdb462", "box"),
    "softmax": ("#fccde5", "box"),
    "SoftmaxOutput": ("#fccde5", "box"),
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Render the graph with graphviz (ref: visualization.py
    plot_network:196).  Returns a ``graphviz.Digraph`` when the graphviz
    package is importable, else the raw DOT source string (write it to a
    .dot file and render offline)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    dot_lines = ["digraph \"%s\" {" % title, "  rankdir=BT;"]
    attr_str = ""
    if node_attrs:
        attr_str = " " + " ".join('%s="%s"' % kv for kv in node_attrs.items())
    idx = {}
    for node in symbol._topo():
        name = node._name or "node%d" % len(idx)
        idx[id(node)] = name
        if node.is_variable():
            if hide_weights and name not in ("data",):
                continue
            dot_lines.append(
                '  "%s" [label="%s" shape=oval fillcolor="#8dd3c7" '
                'style=filled%s];' % (name, name, attr_str))
            continue
        color, shp = _NODE_STYLE.get(node._op.name, ("#d9d9d9", "box"))
        label = "%s\\n%s" % (name, node._op.name)
        if node._op.name in ("Convolution", "Deconvolution"):
            k = node._params.get("kernel", ())
            label += "\\n%s/%s, %s" % ("x".join(map(str, k)),
                                       "x".join(map(str, node._params.get(
                                           "stride", (1,) * len(k)))),
                                       node._params.get("num_filter", "?"))
        elif node._op.name == "FullyConnected":
            label += "\\n%s" % node._params.get("num_hidden", "?")
        dot_lines.append('  "%s" [label="%s" shape=%s fillcolor="%s" '
                         'style=filled%s];' % (name, label, shp, color,
                                               attr_str))
    for node in symbol._topo():
        if node.is_variable():
            continue
        for i in node._inputs:
            b = i._base()
            if b.is_variable() and hide_weights \
                    and (b.name or "") != "data":
                continue
            dot_lines.append('  "%s" -> "%s";'
                             % (idx[id(b)], idx[id(node)]))
    dot_lines.append("}")
    src = "\n".join(dot_lines)
    try:
        import graphviz
        g = graphviz.Source(src, filename=title, format=save_format)
        return g
    except ImportError:
        return src
