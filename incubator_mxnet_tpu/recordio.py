"""RecordIO: record-packed binary dataset files.

TPU-native reimplementation of python/mxnet/recordio.py over the dmlc-core
RecordIO wire format (3rdparty dmlc-core recordio.h, surfaced through the C
API MXRecordIOWriter*/Reader* functions — SURVEY §2.1 Data IO row):

  [kMagic:4B][cflag:3bits|length:29bits:4B][payload][pad to 4B]

Pure Python here (the hot path — image decode + augment — lives in the C++
data plane later; the *format* must be bit-compatible so .rec files
interchange with the reference).
"""
from __future__ import annotations

import ctypes
import os
import struct
import numbers
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a


_MAX_REC_LEN = (1 << 29) - 1   # 29-bit length field (dmlc recordio)


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO(object):
    """Sequential record reader/writer (ref: recordio.py class MXRecordIO →
    dmlc::RecordIOWriter/Reader)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        from . import _native
        from . import config as _config
        use_native = (_config.get_bool("NATIVE_IO", True)
                      and _native.available())
        if self.flag == "w":
            self.writable = True
            self.handle = (_native.NativeRecordWriter(self.uri) if use_native
                           else open(self.uri, "wb"))
        elif self.flag == "r":
            self.writable = False
            self.handle = (_native.NativeRecordReader(self.uri) if use_native
                           else open(self.uri, "rb"))
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self._native_handle = use_native
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Override pickling behavior (ref: recordio.py __getstate__)."""
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("handle", None)
        d.pop("_lock", None)
        d.pop("fidx", None)
        return d

    def __setstate__(self, d):
        import threading
        self.__dict__ = d
        self.handle = None
        if "idx_path" in d:
            self._lock = threading.Lock()
            self.fidx = None
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            self.open()

    def close(self):
        if not self.is_open:
            return
        self.handle.close()
        self.is_open = False

    def reset(self):
        """ref: recordio.py reset."""
        self.close()
        self.open()

    def write(self, buf):
        """Write one record (ref: MXRecordIOWriterWriteRecord; native
        path: src/io/recordio.cc MXTPURecordIOWriterWrite)."""
        assert self.writable
        data = bytes(buf)
        if self._native_handle:
            self.handle.write(data)     # framing done in C++
            return
        # dmlc recordio: no escaping needed for our write path because we
        # write magic-aligned records with explicit length framing.
        # Payloads that overflow the 29-bit length field split into
        # begin(1)/middle(2)/end(3) parts (dmlc multi-part convention —
        # the reader accumulates until cflag 0 or 3); a single chunk
        # would silently bleed length bits into cflag
        max_len = _MAX_REC_LEN

        def emit(cflag, view):
            self.handle.write(struct.pack("<II", _kMagic,
                                          _encode_lrec(cflag, len(view))))
            self.handle.write(view)
            pad = (4 - len(view) % 4) % 4
            if pad:
                self.handle.write(b"\x00" * pad)

        if len(data) <= max_len:
            emit(0, data)
            return
        mv = memoryview(data)   # stream chunks, no payload copies
        off = 0
        while off < len(data):
            n = min(max_len, len(data) - off)
            cflag = 1 if off == 0 else (3 if off + n >= len(data) else 2)
            emit(cflag, mv[off:off + n])
            off += n

    def read(self):
        """Read one record, or None at EOF (ref: MXRecordIOReaderReadRecord;
        native path: src/io/recordio.cc MXTPURecordIOReaderNext)."""
        assert not self.writable
        if self._native_handle:
            return self.handle.read()   # whole-record read in C++
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise IOError("Invalid RecordIO magic in %s" % self.uri)
        cflag, length = _decode_lrec(lrec)
        data = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        # multi-part records (cflag != 0) are concatenated
        while cflag in (1, 2):  # begin/middle of a split record
            head = self.handle.read(8)
            magic, lrec = struct.unpack("<II", head)
            cflag, length = _decode_lrec(lrec)
            data += self.handle.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            if cflag == 3:  # end
                break
        return data

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via an index file (ref: recordio.py
    MXIndexedRecordIO; idx format: "key\\tposition\\n")."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        import threading
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        # seek+read must be atomic under the threaded DataLoader (the
        # reference used per-process handles; we share one handle + a lock)
        self._lock = threading.Lock()
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        if self.writable:
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()

    def seek(self, idx):
        """ref: recordio.py seek."""
        assert not self.writable
        pos = self.idx[idx]
        self.handle.seek(pos)

    def read_idx(self, idx):
        """ref: recordio.py read_idx (thread-safe: seek+read is atomic)."""
        with self._lock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf):
        """ref: recordio.py write_idx."""
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# header packed in front of each record's payload
# (ref: recordio.py IRHeader + pack: struct IRHeader {flag, label, id, id2})
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes into one record payload (ref: recordio.py
    pack; flag>0 means `label` is a flag-length float array appended after
    the header)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        packed = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                             header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        packed = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                             header.id2) + label.tobytes()
    return packed + s


def unpack(s):
    """Inverse of pack (ref: recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + header into a record (ref: recordio.py pack_img)."""
    import cv2
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Decode a packed image record (ref: recordio.py unpack_img)."""
    import cv2
    header, s = unpack(s)
    img = np.frombuffer(s, dtype=np.uint8)
    img = cv2.imdecode(img, iscolor)
    return header, img
