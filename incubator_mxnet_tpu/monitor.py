"""Monitor: per-layer output statistics for debugging (ref:
python/mxnet/monitor.py:33 + MXExecutorSetMonitorCallback,
src/executor/graph_executor.cc:121,1447).

The executor calls ``Monitor.toc`` hooks with every intermediate output so
users can print norms/means per layer — the observability path of SURVEY
§5.5.  Our traced executor exposes the same tap via its node callback.
"""
from __future__ import annotations

import logging
import re
from math import sqrt

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    """ref: monitor.py class Monitor."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """returns |x|/size(x), async execution."""
                v = x.asnumpy()
                return abs(v).sum() / v.size
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        """Callback attached to executors (ref: monitor.py stat_helper)."""
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        """ref: monitor.py install → set_monitor_callback."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this step (ref: monitor.py tic)."""
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_arrays:
                    array.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Finish a step; returns collected stats (ref: monitor.py toc)."""
        if not self.activated:
            return []
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()
        for exe in self.exes:
            for name, array in zip(exe._arg_names, exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = ""
            for v in v_list:
                s += str(v) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """ref: monitor.py toc_print."""
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
