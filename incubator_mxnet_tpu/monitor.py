"""Monitor: per-layer output statistics for debugging (ref:
python/mxnet/monitor.py:33 + MXExecutorSetMonitorCallback,
src/executor/graph_executor.cc:121,1447).

The executor calls ``Monitor.toc`` hooks with every intermediate output so
users can print norms/means per layer — the observability path of SURVEY
§5.5.  Our traced executor exposes the same tap via its node callback.
"""
from __future__ import annotations

import logging
import re
from math import sqrt

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    """ref: monitor.py class Monitor."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                """returns |x|/size(x), async execution."""
                v = x.asnumpy()
                return abs(v).sum() / v.size
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    @staticmethod
    def _is_deferred(array):
        from .engine import _Pending
        d = getattr(array, "_data", None)
        return type(d) is _Pending and d.value is None

    def stat_helper(self, name, array):
        """Callback attached to executors (ref: monitor.py stat_helper).

        Concrete arrays are reduced to their stat immediately (no tensor
        is pinned).  DEFERRED arrays (a bulk segment in flight) queue the
        reference instead, and ``toc()`` computes the stat behind one
        engine flush — computing here would force an ``asnumpy()``
        materialization per intermediate output, fragmenting every bulk
        segment the monitored step built (and miscounting the flushes as
        user ``read``s)."""
        if not self.activated or not self.re_prog.match(name):
            return
        self._enqueue(name, array)

    def _enqueue(self, name, array):
        if self._is_deferred(array):
            self.queue.append((self.step, name, array, True))
        else:
            self.queue.append((self.step, name, self.stat_func(array),
                               False))

    def install(self, exe):
        """ref: monitor.py install → set_monitor_callback."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        """Open a collection window if this step is due
        (ref: monitor.py tic contract)."""
        self.activated = self.step % self.interval == 0
        if self.activated:
            self.queue = []
        self.step += 1

    def _fmt(self, value):
        if isinstance(value, NDArray):
            value = value.asnumpy()
        if isinstance(value, (list, tuple)):
            return "  ".join(self._fmt(v) for v in value)
        return str(value)

    def toc(self):
        """Close the window: append matching *parameter* stats to the
        layer-output stats gathered by the executor tap, and return
        [(step, name, formatted stat)] (ref: monitor.py toc contract).

        All queued arrays materialize behind ONE engine flush tagged
        ``cause="monitor"`` — ``flush_stats()`` attributes monitoring
        cost to the monitor, not to user reads."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            exe.outputs and exe.outputs[0].wait_to_read()
            for name, array in zip(exe._arg_names, exe.arg_arrays):
                if self.re_prog.match(name):
                    self._enqueue(name, array)
        if any(lazy for _, _, _, lazy in self.queue):
            from . import engine
            engine.flush(cause="monitor")
        entries = [(step, name,
                    self.stat_func(payload) if lazy else payload)
                   for step, name, payload, lazy in self.queue]
        if self.sort:
            entries = sorted(entries, key=lambda e: e[1])
        self.queue = []
        return [(step, name, self._fmt(stat))
                for step, name, stat in entries]

    def toc_print(self):
        """Log everything toc() collected (ref: monitor.py toc_print)."""
        for step, name, text in self.toc():
            logging.info("monitor step %d  %s: %s", step, name, text)
