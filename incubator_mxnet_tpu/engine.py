"""Engine op-bulking: defer eager ops, replay them as ONE XLA program.

The reference's engine amortized per-op push overhead by appending
consecutive eager ops into a bulk segment executed as one engine op
(src/engine/threaded_engine.h:472-509 BulkAppend/BulkFlush,
MXNET_EXEC_BULK_EXEC_*).  The TPU-native analogue: inside a

    with mx.engine.bulk(64):
        for ...:
            eager small ops

scope, pure eager op invocations are RECORDED instead of dispatched; the
pending program is flushed — compiled (once, cached by program shape) and
executed as a single jitted replay — when the scope closes, the segment
reaches ``size`` ops, or any deferred value is materialized (asnumpy,
_read, in-place write, autograd capture).  Steady-state loops hit the
replay cache, so N small ops cost one dispatch (measured ~5x on the
eager micro-benchmark, bench_eager.py).

Autograd-recording ops ARE deferrable (round 4 — the reference bulks
*training* segments first and foremost, MXNET_EXEC_BULK_EXEC_TRAIN,
threaded_engine.h:472-509): a segment containing recorded ops becomes
ONE tape node at flush — the forward is the single jitted replay, and
the backward is a single jitted vjp of the whole replay program, so an
N-op recorded chain costs one dispatch forward and one backward instead
of N + 2N.  Ops that ran under ``autograd.pause()`` inside the segment
are wrapped in ``stop_gradient`` so the tape semantics match eager
execution exactly.

``out=`` stores and mutating ops (optimizer updates) ARE deferrable
(round 5, matching the reference's bulking of optimizer updates inside
train segments): the write target's buffer is rebound to the pending
output at record time, provided the target is a plain non-view NDArray
and the inferred output matches its shape/dtype exactly — otherwise the
op runs eagerly with the usual astype/write-through fixups.

VIEW ops are deferrable (round 6 — the reference bulks the reshape/
transpose glue of real model bodies into the same segment,
threaded_engine.h:472-509): a view taken of a deferred value becomes a
new _Pending whose program node is the corresponding shape op
(``_bulk_view_extract``: flat slice + reshape, exactly NDArray._read's
concrete math), so reshape/reshape_like/expand_dims/``__getitem__``
basic slicing/at/slice over a pending keep the segment open —
transpose/swapaxes/squeeze are ordinary registered ops and defer
through the normal path.  Write-through to a deferred view records a
``_bulk_view_write`` (lax.dynamic_update_slice into the base's flat
buffer) in the same program and rebinds the base to the new pending.
Liveness treats base and view as one ownership group: the view holds a
strong ref to its base NDArray, so a live view keeps its base's pending
live, and a dead view's extract node is eliminated like any other dead
value.  Views still MATERIALIZE (one flush, counted under the ``view``
flush cause) when the base pending belongs to another scope/segment,
for sparse storage, and for fancy/multi-axis indexing — those read
concrete buffers by construction.

Out of scope for deferral (dispatched eagerly, exactly as before):
recorded ops with ``out=``, sparse storage, ops that manage their own
mesh placement (no_jit), and NaiveEngine mode.

Strict mode (round 7, ``GRAFT_ENGINE_CHECK=1`` or ``set_engine_check``):
every segment is verified against the hazards the deferral machinery
could silently mis-handle — a read/write version vector per base+view
ownership group catches stale-extract write-after-read (EH101) and
double-write rebinds (EH102) at record time; flush validates operand
references against the ``ext`` set (EH103) and replays the segment
UNFUSED, bit-comparing every live output against the fused result (the
fusion-equivalence oracle, EH104).  Violations raise structured
``EngineHazardError``s (analysis/engine_check.py; docs/static_analysis.md).
Debug-only: the oracle doubles execution per flush.

Every flush is attributed to a cause — ``scope-close`` (bulk.__exit__),
``size-cap`` (segment hit ``size``), ``view`` (a non-deferrable view
materialized its base), ``read`` (asnumpy/_read of a deferred value),
``autograd`` (backward landing the segment's tape node) — and the
per-flush instruction count feeds a segment-length histogram; see
``flush_stats()`` / ``reset_flush_stats()``.  bench_eager.py reports
both so segment fragmentation is visible per round.
"""
from __future__ import annotations

import os
import threading
import time
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from .analysis.engine_check import (EngineHazardError,
                                    check_segment_integrity, oracle_compare)
from .analysis import tsan as _tsan
from . import profiler as _profiler
from .telemetry import blackbox as _blackbox
from .telemetry import lens as _lens
from .telemetry import metrics as _tmetrics
from .telemetry import tracing as _ttracing

__all__ = ["bulk", "offband", "in_bulk", "flush", "flush_stats",
           "reset_flush_stats",
           "EngineHazardError", "engine_check_enabled", "set_engine_check",
           "BoundedCache", "cache_sizes", "flatten_arrays", "unflatten",
           "split_flat", "colocate"]


# --- strict-mode switch (GRAFT_ENGINE_CHECK=1) -----------------------------
# Read per bulk-scope entry (not at import) so tests and debug sessions can
# toggle it without reimporting; set_engine_check overrides the env var.
_engine_check_override = None


def set_engine_check(flag):
    """Force strict mode on/off (None = defer to GRAFT_ENGINE_CHECK)."""
    global _engine_check_override
    _engine_check_override = flag


def engine_check_enabled():
    if _engine_check_override is not None:
        return bool(_engine_check_override)
    return os.environ.get("GRAFT_ENGINE_CHECK", "").strip().lower() \
        in ("1", "true", "yes", "on")


class _Pending(object):
    """Placeholder for a deferred value (knows shape/dtype for metadata
    queries; ``value`` is filled at flush).  ``owners`` holds weakrefs to
    the NDArrays exposing this value: a pending with no live owner at
    flush time is dead (an intermediate the chain rebound) and is NOT
    returned from the replay program — dead-value elimination keeps the
    per-flush output count at what the user actually kept."""
    __slots__ = ("shape", "dtype", "slot", "value", "state", "epoch",
                 "owners", "error", "__weakref__")

    def __init__(self, shape, dtype, slot, state):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.slot = slot
        self.value = None
        self.state = state
        self.epoch = state.epoch
        self.owners = []
        self.error = None

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n


class _BulkState(object):
    def __init__(self, size, check=False):
        self.size = size
        self.check = bool(check)  # strict-mode verifier (GRAFT_ENGINE_CHECK)
        # the scope belongs to the thread that opened it: a deferred
        # value resolved from any OTHER thread flushes this state while
        # its owner may still be recording — grafttsan's EH203 hazard
        self.owner_tid = threading.get_ident()
        if _tsan.enabled():
            _tsan.segment_open(self)    # remember the opening stack
        self.extract_meta = {}   # id(extract _Pending) -> (view weakref,
        #                          base weakref, base._version at record):
        #                          the read side of the strict-mode
        #                          version vector (writes bump
        #                          NDArray._version, so staleness is
        #                          recorded-version != current-version)
        self.epoch = 0           # bumped per flush: "t" refs are only
        #                          valid within their own segment
        self.instructions = []   # (op_name, params, pkey, is_train,
        #                           in_refs, rng_slot, n_out, rec)
        self.ext = []            # concrete jax operands (program inputs)
        self.ext_ids = {}        # id(owner NDArray)|id(value) -> slot
        self.ext_owners = []     # weakref to the NDArray exposing a slot
        self.ext_pins = []       # strong refs pinning owner ids for the
        #                          segment (id() recycling would corrupt
        #                          the dedup table otherwise)
        self.pendings = []       # _Pending objects in slot order
        self.any_recorded = False
        self.seg_id = None       # telemetry segment id, assigned at the
        #                          first recorded instruction (flush spans
        #                          + record-event flow links share it)
        self.flow_marks = []     # instruction indices that emitted a flow
        #                          start ("s") — flush finishes exactly
        #                          these, never a dangling arrow

    def add_ext(self, v, owner=None):
        # dedup by (owner NDArray, buffer): two distinct NDArrays can
        # share a buffer (x and x.detach()) but must keep separate
        # gradient slots, and one NDArray can RE-BIND its buffer
        # mid-segment (an in-place write between deferred ops) and must
        # then get a fresh slot — keying on either identity alone loses
        # one of the two cases.  Owners are pinned in ext_pins so ids
        # cannot be recycled mid-segment (values are pinned via ext).
        key = (id(owner) if owner is not None else None, id(v))
        slot = self.ext_ids.get(key)
        if slot is None:
            self.ext.append(v)
            self.ext_owners.append(weakref.ref(owner) if owner is not None
                                   else None)
            self.ext_pins.append(owner)
            slot = len(self.ext) - 1
            self.ext_ids[key] = slot
        return slot


class BoundedCache(object):
    """Insertion/recency-ordered dict with size-bounded LRU eviction.

    The engine's program caches (`_replay_cache`, `_infer_cache`,
    `_seg_vjp_cache`) and the optimizer's fused-bucket-update cache grow
    one entry per distinct program shape; a long-running trainer that
    keeps changing shapes (dynamic batching, progressive resizing) would
    otherwise hold every compiled program it ever built.  The bound is
    ``GRAFT_REPLAY_CACHE_SIZE`` (default 1024; <= 0 means unbounded),
    read at every insertion so tests and live sessions can re-tune it.
    Eviction drops the least-recently-used entry — closures that already
    captured an evicted value (e.g. a segment vjp held by live tape
    nodes) keep working; only future lookups rebuild."""

    DEFAULT_SIZE = 1024

    def __init__(self, env="GRAFT_REPLAY_CACHE_SIZE"):
        from collections import OrderedDict
        self._env = env
        self._d = OrderedDict()

    def _bound(self):
        try:
            return int(os.environ.get(self._env, str(self.DEFAULT_SIZE)))
        except ValueError:
            return self.DEFAULT_SIZE

    def get(self, key, default=None):
        try:
            self._d.move_to_end(key)
        except KeyError:
            return default
        return self._d[key]

    def __getitem__(self, key):
        self._d.move_to_end(key)
        return self._d[key]

    def __setitem__(self, key, value):
        self._d[key] = value
        self._d.move_to_end(key)
        bound = self._bound()
        if bound > 0:
            while len(self._d) > bound:
                self._d.popitem(last=False)

    def __contains__(self, key):
        return key in self._d

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def clear(self):
        self._d.clear()


_tls = threading.local()
_replay_cache = BoundedCache()
_infer_cache = BoundedCache()   # (op, input sig, params, train) -> output
# sig; shape inference via jax.eval_shape costs ~a dispatch itself, so
# recording would be slower than executing without this memo

_FLUSH_CAUSES = ("scope-close", "size-cap", "view", "read", "autograd",
                 "monitor")
_flush_causes = {c: 0 for c in _FLUSH_CAUSES}
_segment_hist = {}   # instructions-per-flush -> count


def flush_stats():
    """Flush-cause counters and the segment-length histogram (counted
    only for flushes that actually executed instructions)."""
    return {"causes": dict(_flush_causes),
            "segment_lengths": dict(_segment_hist)}


def reset_flush_stats():
    for c in _FLUSH_CAUSES:
        _flush_causes[c] = 0
    _segment_hist.clear()
    _tmetrics.reset_engine_metrics()   # keep both views of one event
    #                                    stream in agreement


def _current():
    return getattr(_tls, "state", None)


class bulk(object):
    """Context manager: defer up to ``size`` eager ops per segment."""

    def __init__(self, size=64):
        self.size = max(int(size), 1)
        self._prev = None

    def __enter__(self):
        self._prev = _current()
        _tls.state = _BulkState(self.size, check=engine_check_enabled())
        return self

    def __exit__(self, *exc):
        try:
            flush(cause="scope-close")
        finally:
            _tls.state = self._prev


class offband(object):
    """PUBLIC API — dispatch eagerly ALONGSIDE an open bulk segment
    without joining or flushing it.

    Introduced for graftlap (the Trainer's bucket scheduler firing a
    gradient allreduce from a grad-ready hook mid-backward): work issued
    inside the scope must not become a deferred instruction of whatever
    segment the caller happens to have open (it has to hit the wire
    NOW), and it must not force that segment to materialize either (the
    deferred ops are unrelated).  Inside this scope the bulk state is
    stashed and ops dispatch through the ordinary eager path — XLA's
    async dispatch keeps them concurrent with everything else — while
    the surrounding segment's pending program survives untouched and
    flushes at its own boundary.

    graftstep rides the same rail: a compiled whole-step dispatch
    (``gluon/step_compile.py``) flushes the caller's open segment first
    (its inputs may be deferred) and then runs under this scope, so the
    single fwd+bwd+update program and its boundary ``reduce_many`` never
    join — or force — a user's bulk segment.

    Now documented for user code (ROADMAP "engine offband for user
    code"): any *dispatch now, alongside the open segment* need fits —
    async checkpointing, metric pushes, ad-hoc collectives::

        with mx.engine.bulk(64):
            body()                       # defers into one segment
            with mx.engine.offband():
                checkpoint_shard.copy()  # dispatches immediately
            more_body()                  # same segment keeps recording

    Values produced inside the scope are ordinary concrete NDArrays;
    values from the surrounding segment remain deferred and reading one
    inside the scope still materializes its segment (same rule as any
    read).  See docs/observability.md "Off-band dispatch"."""

    def __enter__(self):
        self._prev = _current()
        _tls.state = None
        return self

    def __exit__(self, *exc):
        _tls.state = self._prev


def in_bulk():
    """True when the calling thread has an open ``bulk`` segment.

    The graftstep compiled dispatch consults this to decide whether its
    pre-dispatch ``flush(cause="step_compile")`` has anything to land —
    keeping the flush-cause taxonomy honest (no zero-op "step_compile"
    causes on the common non-bulk path)."""
    return _current() is not None


def maybe_defer(op, params, vals, is_train, kw, rec=False, nd_inputs=None,
                out_reqs=None):
    """Called from the eager invoke: record the op if a bulk scope is
    active and every input is deferrable.  Returns a tuple of _Pending
    outputs, or None to dispatch eagerly.  ``rec`` marks ops being taped
    by autograd: the flush builds one tape node for the whole segment;
    ``nd_inputs`` are the NDArray wrappers (gradient delivery targets).
    ``out_reqs`` — [(slot, shape, dtype_str)] constraints from ``out=``
    write targets: deferral is refused (BEFORE anything is recorded)
    unless the inferred output matches exactly, because a deferred store
    rebinds the target's buffer without the eager path's astype/reshape
    fixups."""
    st = _current()
    if st is None:
        return None
    if len(st.instructions) >= st.size:
        # flush BEFORE recording the next op (never right after one: the
        # freshly created outputs get their owner refs only once invoke
        # wraps them — flushing in between would mis-classify them dead)
        flush(cause="size-cap")
    # deferred records are traced as near-zero "record" events, never as
    # op runtime: the cost lands on the owning segment's flush span, and
    # a chrome-trace flow (s→f) draws the record→flush attribution arrow
    trace = _ttracing.record_active()
    t0 = _profiler._now_us() if trace else 0.0
    from .ops.registry import _hashable
    # stage input refs WITHOUT touching st yet: if we bail (stale
    # pending, failed inference) no orphan ext entries may pollute the
    # replay-cache key
    staged = []
    shapes = []
    for i, v in enumerate(vals):
        if type(v) is _Pending:
            if v.state is not st or v.epoch != st.epoch:
                return None       # cross-scope/segment value: materialize
            staged.append(("t", v, None))
        else:
            owner = nd_inputs[i] if nd_inputs is not None else None
            staged.append(("e", v, owner))
        shapes.append((tuple(v.shape), str(v.dtype)))
    if st.check:
        _strict_check_record(st, op, vals, nd_inputs)
    pkey = _hashable(params)
    ikey = (op.name, tuple(shapes), pkey, bool(is_train))
    out_sig = _infer_cache.get(ikey)
    if out_sig is None:
        try:
            out_sig = op.infer(shapes, params, is_train)
        except Exception:
            return None           # shape inference failed: run eagerly
        _infer_cache[ikey] = out_sig
    if out_reqs is not None:
        for slot, shp, dt in out_reqs:
            if slot >= len(out_sig):
                return None
            oshp, odt = out_sig[slot]
            if tuple(oshp) != tuple(shp) or str(odt) != str(dt):
                return None
    in_refs = [(tag, v.slot if tag == "t" else st.add_ext(v, owner))
               for tag, v, owner in staged]
    rng_slot = st.add_ext(kw["rng"]) if "rng" in kw else None
    outs = []
    for shp, dt in out_sig:
        p = _Pending(shp, dt, len(st.pendings), st)
        st.pendings.append(p)
        outs.append(p)
    st.instructions.append((op.name, dict(params), pkey,
                            bool(is_train), tuple(in_refs), rng_slot,
                            len(outs), bool(rec)))
    st.any_recorded |= bool(rec)
    if st.seg_id is None:
        st.seg_id = _ttracing.next_segment_id()
    if trace:
        idx = len(st.instructions) - 1
        st.flow_marks.append(idx)
        _ttracing.deferred_op_event(op.name, t0, _profiler._now_us(),
                                    st.seg_id, idx)
    return tuple(outs)


def _strict_check_record(st, op, vals, nd_inputs):
    """Record-time hazard checks (GRAFT_ENGINE_CHECK=1): consult the
    read/write version vector of each input's base+view ownership group.
    Reads are the extract_meta entries stamped by defer_view_read; writes
    are NDArray._version bumps — staleness is a version mismatch."""
    for pos, v in enumerate(vals):
        if type(v) is not _Pending:
            continue
        meta = st.extract_meta.get(id(v))
        if meta is None:
            continue
        view_ref, base_ref, ver = meta
        base = base_ref()
        view = view_ref()
        # Staleness is only hazardous when the pending arrives THROUGH
        # the view it extracts: eager semantics would re-read the view
        # post-write there (_read_deferred re-extracts, so a stale
        # arrival means that guard was bypassed).  Reaching the same
        # pending through a different owner — e.g. `w[:] = v` stored the
        # extract into a copy target — is a legal snapshot read of the
        # pre-write value, exactly what the recorded program replays.
        consumer = (nd_inputs[pos] if nd_inputs is not None
                    and pos < len(nd_inputs) else None)
        if base is not None and view is not None and consumer is view \
                and base._version != ver:
            raise EngineHazardError(
                "EH101", "op %r consumes view (shape %s offset %d) "
                "through a _bulk_view_extract recorded at base version %d "
                "but the base has been rebound to version %d since — the "
                "fused replay would read the pre-write value where eager "
                "execution reads the post-write one" % (
                    op.name, view._shape, view._offset, ver, base._version),
                op=op.name, input=pos, recorded_version=ver,
                current_version=base._version,
                group_views=len(base._live_views()))
    if op.name == "_bulk_view_write" and nd_inputs:
        base = nd_inputs[0]
        if base is not None and vals and base._data is not vals[0]:
            raise EngineHazardError(
                "EH102", "_bulk_view_write over a base operand that is no "
                "longer the base's current binding (version %d) — the "
                "rebind would silently discard intervening write(s) "
                "(lost update); ownership group has %d live view(s)"
                % (base._version, len(base._live_views())),
                base_version=base._version,
                group_views=len(base._live_views()))


def defer_view_read(view):
    """Record a ``_bulk_view_extract`` node for a (base, offset, shape)
    view whose base is deferred: the view's value becomes a new _Pending
    in the same program instead of a materialization point.  Returns the
    pending (registered as owned by ``view``), or None when deferral is
    impossible (no scope / cross-scope base) — caller falls back to the
    concrete read, which flushes under the ``view`` cause.

    Recorded with rec=False: in eager execution a view created outside
    recording enters the tape as a constant leaf, so the replay's
    stop_gradient wrap reproduces those semantics exactly.  Views created
    *inside* record() never reach here — reshape/__getitem__ route through
    the registered Reshape/slice_axis ops under recording."""
    st = _current()
    if st is None:
        return None
    base = view._base
    if type(base._data) is not _Pending or base._data.value is not None:
        return None
    from .ops.registry import get_op
    pend = maybe_defer(get_op("_bulk_view_extract"),
                       {"offset": int(view._offset),
                        "shape": tuple(view._shape)},
                       [base._data], False, {}, nd_inputs=[base])
    if pend is None:
        return None
    p = pend[0]
    p.owners.append(weakref.ref(view))
    if st.check:
        # read-side entry of the strict-mode version vector: this extract
        # is valid exactly while the base stays at its current version
        st.extract_meta[id(p)] = (weakref.ref(view), weakref.ref(base),
                                  base._version)
    return p


def defer_view_write(view, value):
    """Record a ``_bulk_view_write`` node: the base's buffer is rebound to
    a new pending whose program node scatters ``value`` (concrete array or
    same-segment pending) over the view's span — write-through to a
    deferred view stays inside the segment.  Returns the base's new
    pending (owned by the base NDArray), or None to fall back to the
    concrete write-through path."""
    st = _current()
    if st is None:
        return None
    base = view._base
    bval = base._data
    if not (type(bval) is _Pending and bval.value is None) \
            and not (type(value) is _Pending and value.value is None):
        return None          # nothing deferred: the concrete path is fine
    from .ops.registry import get_op
    pend = maybe_defer(get_op("_bulk_view_write"),
                       {"offset": int(view._offset)},
                       [bval, value], False, {}, nd_inputs=[base, None])
    if pend is None:
        return None
    p = pend[0]
    p.owners.append(weakref.ref(base))
    return p


def resolve(pending, cause="read"):
    """Materialize one deferred value (flushes its segment if needed)."""
    if pending.value is None:
        if _tsan._ACTIVE[0]:
            # a foreign-thread resolve flushes the owner's open segment
            # mid-recording (EH203) — report before the flush proceeds
            _tsan.check_segment(pending.state)
        flush(pending.state, cause=cause)
    if pending.error is not None:
        raise RuntimeError("bulk engine: the deferred segment holding this "
                           "value failed to execute") from pending.error
    if pending.value is None:  # liveness tracking invariant violated
        raise RuntimeError("bulk engine: deferred value was eliminated as "
                           "dead but later read — please report")
    return pending.value


def _build_replay(instrs, live):
    """Pure replay fn over the ext operand list.  Ops taped by autograd
    keep their gradients; ops that ran outside recording (pause scopes,
    non-differentiable ops) are wrapped in stop_gradient so the segment's
    single vjp matches eager tape semantics exactly."""
    from .ops.registry import get_op
    plan = [(get_op(name).raw(p, train), in_refs, rng_slot, n_out, rec)
            for name, p, _k, train, in_refs, rng_slot, n_out, rec in instrs]

    def replay(ext_vals):
        tmp = []
        for raw, in_refs, rng_slot, n_out, rec in plan:
            args = [ext_vals[i] if tag == "e" else tmp[i]
                    for tag, i in in_refs]
            kw = {"rng": ext_vals[rng_slot]} if rng_slot is not None \
                else {}
            res = raw(*args, **kw)
            if not isinstance(res, tuple):
                res = (res,)
            if not rec:
                res = tuple(jax.lax.stop_gradient(r) for r in res)
            tmp.extend(res)
        return tuple(tmp[i] for i in live)

    return replay


def _rec_reachable_ext(instrs):
    """Ext slots whose gradient path reaches a recorded instruction
    through recorded-op chains only (stop_gradient blocks every other
    path, so those slots are the exact tape-input set).  Inputs an op
    declares ``nograd_inputs`` never receive gradient in eager backward
    (_run_backward's per-op skip), so slots reaching recorded ops SOLELY
    through such positions are excluded too — e.g. BatchNorm's
    moving_mean/moving_var (inputs 3-4) must not land on the tape node."""
    from .ops.registry import get_op
    ext_slots = set()
    pend_deps = []
    for name, _p, _k, _train, in_refs, _rng, n_out, rec in instrs:
        if rec:
            nograd = set(get_op(name).nograd_inputs)
            deps = set()
            for pos, (tag, i) in enumerate(in_refs):
                if pos in nograd:
                    continue
                if tag == "e":
                    deps.add(i)
                else:
                    deps |= pend_deps[i]
            ext_slots |= deps
            out_deps = frozenset(deps)
        else:
            out_deps = frozenset()
        pend_deps.extend([out_deps] * n_out)
    return ext_slots


def _record_segment_node(key, replay, ext, ext_owners, pendings, live,
                         instrs):
    """One tape node for the whole recorded segment: forward already ran
    (the replay); backward is a single jitted vjp of the replay program
    w.r.t. the float ext operands (the reference's train-segment bulking,
    threaded_engine.h MXNET_EXEC_BULK_EXEC_TRAIN)."""
    from . import autograd
    from .operator import Operator

    grad_slots = [i for i, v in enumerate(ext)
                  if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)]
    # Only ext operands that can actually RECEIVE gradient belong on the
    # tape node: slots feeding recorded instructions, directly or through
    # chains of recorded ops (non-recorded outputs are stop_gradient'd,
    # so paths through them are dead — and eager semantics would not put
    # those inputs on the tape at all).
    reachable = _rec_reachable_ext(instrs)
    in_pairs = [(s, ext_owners[s]()) for s in grad_slots
                if s in reachable
                and ext_owners[s] is not None and ext_owners[s]() is not None]
    out_pairs = []          # (position in `live` results, owner NDArray)
    for pos, i in enumerate(live):
        p = pendings[i]
        if not jnp.issubdtype(jnp.dtype(p.dtype), jnp.floating):
            continue
        owner = next((w() for w in p.owners if w() is not None), None)
        if owner is not None:
            out_pairs.append((pos, owner))
    if not in_pairs or not out_pairs:
        return
    out_pos = tuple(pos for pos, _ in out_pairs)

    vjp_key = (key, tuple(grad_slots), out_pos)
    vjp_fn = _seg_vjp_cache.get(vjp_key)
    if vjp_fn is None:
        def vjp_calc(ext_vals, cts):
            def f(fvals):
                full = list(ext_vals)
                for s, v in zip(grad_slots, fvals):
                    full[s] = v
                outs = replay(full)
                return tuple(outs[pos] for pos in out_pos)
            _, pullback = jax.vjp(f, tuple(ext_vals[s]
                                           for s in grad_slots))
            return pullback(tuple(cts))[0]
        vjp_fn = jax.jit(vjp_calc)
        _seg_vjp_cache[vjp_key] = vjp_fn

    keep = {s: j for j, s in enumerate(grad_slots)}
    in_slots = [s for s, _ in in_pairs]
    nd_inputs = [nd for _, nd in in_pairs]
    nd_outputs = [nd for _, nd in out_pairs]

    def seg_vjp(ct):
        cts = ct if isinstance(ct, tuple) else (ct,)
        grads = vjp_fn(ext, tuple(cts))
        return tuple(grads[keep[s]] for s in in_slots)

    def seg_fn(*in_vals):
        full = list(ext)
        for s, v in zip(in_slots, in_vals):
            full[s] = v
        outs = replay(full)
        picked = tuple(outs[pos] for pos in out_pos)
        return picked[0] if len(picked) == 1 else picked

    op = Operator("_BulkSegment", lambda *a: a,
                  num_inputs=len(nd_inputs), num_outputs=len(nd_outputs))
    # re-wrap outputs? no: the live NDArrays already exist — record against
    # them so downstream recorded ops chain through this node
    autograd._record(op, nd_inputs, nd_outputs, seg_vjp, fn=seg_fn)


def flush(state=None, cause="read"):
    """Compile (cached) + run the pending segment; fill every _Pending."""
    st = state if state is not None else _current()
    if st is None or not st.instructions:
        return
    _flush_causes[cause] = _flush_causes.get(cause, 0) + 1
    _segment_hist[len(st.instructions)] = \
        _segment_hist.get(len(st.instructions), 0) + 1
    _tmetrics.engine_flush(cause, len(st.instructions))
    instrs = st.instructions
    ext = st.ext
    ext_owners = st.ext_owners
    pendings = st.pendings
    recorded = st.any_recorded
    seg_id = st.seg_id
    flow_marks = st.flow_marks
    # reset the scope so new ops start a fresh segment (and so re-entrant
    # flushes from _read during execution see an empty program)
    st.instructions, st.ext, st.pendings = [], [], []
    st.ext_ids = {}
    st.ext_owners = []
    st.ext_pins = []
    st.any_recorded = False
    st.extract_meta = {}
    st.seg_id = None
    st.flow_marks = []
    st.epoch += 1

    err = None
    if st.check:
        # EH103 — validate operand references AFTER the state reset, so a
        # hazard raised here leaves the scope reusable (the scope-close
        # flush sees an empty program instead of re-raising); stamp the
        # hazard on every pending so later reads surface IT, not the
        # misleading liveness invariant error
        try:
            check_segment_integrity(instrs, len(ext))
        except EngineHazardError as exc:
            for p in pendings:
                p.error = exc
            err = exc

    # only values still EXPOSED through a live NDArray leave the program:
    # the owner must not just be alive, its buffer must still be this
    # pending — a chained out= store rebinds the owner to each successive
    # pending, and without the `_data is p` check every superseded
    # intermediate would escape the program as a dead output (review
    # finding, round 5: N-long update chains shipped N-1 dead buffers).
    # A view owner additionally needs its extract to be CURRENT: once the
    # base version moves past the view's cache, every read recomputes
    # from the base and the stale extract can never be resolved — it is
    # dead even though `_data is p` still holds
    live = tuple(i for i, p in enumerate(pendings)
                 if any(o is not None and o._data is p
                        and (o._base is None
                             or o._cache_version == o._base._version)
                        for o in (w() for w in p.owners)))
    key = (tuple((name, pkey, train, in_refs, rng_slot, n_out, rec)
                 for name, _p, pkey, train, in_refs, rng_slot, n_out, rec
                 in instrs),
           tuple((tuple(v.shape), str(v.dtype)) for v in ext),
           live)
    prof_on = _profiler._P.active()
    bb_on = _blackbox.enabled()
    span_begin = _profiler._now_us() if prof_on else 0.0
    t0 = time.perf_counter() if bb_on else 0.0
    results = None
    cache_hit = False
    if err is None:
        entry = _replay_cache.get(key)
        cache_hit = entry is not None
        if entry is None:
            replay = _build_replay(instrs, live)
            entry = (jax.jit(replay), replay)
            _replay_cache[key] = entry
        fn, replay = entry
        try:
            # graftwatch bracket: a stalled dispatch shows up in-flight
            # (the watchdog names this segment when it trips)
            with _blackbox.in_flight("engine_flush",
                                     {"segment": seg_id, "cause": cause,
                                      "nodes": len(instrs)}):
                t_dispatch = time.perf_counter()
                results = fn(ext)
                t_dispatched = time.perf_counter()
                if st.check and results:
                    # EH104 — the fusion-equivalence oracle: replay the
                    # segment UNFUSED (the same replay closure outside jit
                    # dispatches each op eagerly) and bit-compare every
                    # live output.  Costs a full second execution per
                    # flush; debug-only by construction.
                    oracle_compare(results, replay(ext), instrs, live)
        except Exception as exc:
            # stamp every pending with the real cause: later reads raise
            # THIS instead of a misleading liveness error
            for p in pendings:
                p.error = exc
            err = exc
    if bb_on:
        fields = {"segment": seg_id, "cause": cause, "nodes": len(instrs),
                  "live_outputs": len(live),
                  "cache": "hit" if cache_hit else "miss",
                  "latency_ms": round((time.perf_counter() - t0) * 1e3, 3)}
        if err is not None:
            fields["error"] = repr(err)
        _blackbox.record("engine_flush", **fields)
    sync_booked = False
    if prof_on or flow_marks:
        # the segment span is where op cost actually lands: with
        # profiler.sync the dispatch blocks until ready, so the span IS
        # device latency (the flush-level analogue of sync-mode op spans).
        # A segment whose records emitted flow starts ALWAYS closes its
        # links here — profiler stopped mid-segment OR replay raised —
        # a dangling arrow would fail the trace validator
        device_time = _profiler.want_sync()
        if device_time and results:
            sync_booked = True
            # device-time lens: under sync mode dispatch→ready is the
            # segment's device latency.  Booked as dispatch + residual
            # wait, EXCLUDING any window between them (the EH104 oracle's
            # host-side unfused replay under GRAFT_ENGINE_CHECK) — an
            # undercount when the device was still busy during it, never
            # an overcount of host work as device time.  Cache-miss
            # spans still include XLA compile (marked cache:"miss").
            _lens.device(t_dispatch, t_dispatched)
            t_block = time.perf_counter()
            jax.block_until_ready(results)
            _lens.device(t_block, time.perf_counter())
        begin = span_begin if prof_on else _profiler._now_us()
        _ttracing.segment_flush_span(
            seg_id, cause, begin, _profiler._now_us(),
            flow_marks, len(instrs), len(live), cache_hit,
            recorded, device_time, error=err is not None)
    if err is None and results and not sync_booked \
            and _lens.pulse_active():
        # graftpulse: no sync mode blocked this dispatch, so hand the
        # result arrays to the 1-thread reaper — it block-until-readies
        # OFF this thread and books dispatch→device-done into this
        # thread's window, filling the device ledger on ordinary async
        # train loops (the sync path above books directly; sync_booked
        # gates the enqueue so the two can never double-book one span).
        # Under GRAFT_ENGINE_CHECK the EH104 oracle ran a FULL host-side
        # unfused replay after the dispatch: start the span now instead
        # — an undercount of device time at worst, never host work
        # booked as device (the sync path's exact invariant)
        _lens.device_async(results,
                           time.perf_counter() if st.check else t_dispatch)
    # graftpulse memory timeline: the flush boundary is the allocation
    # watermark sample point (one allocator-counter read; auto-disabled
    # on backends that report none)
    _lens.mem_sample("flush:%s" % cause)
    if err is not None:
        raise err
    for i, v in zip(live, results):
        pendings[i].value = v
    if recorded:
        _record_segment_node(key, replay, ext, ext_owners, pendings, live,
                             instrs)
    if results:
        # nd.waitall()'s WaitForAll contract covers bulk dispatches too
        from .ndarray import ndarray as _nd
        devs = getattr(results[0], "devices", None)
        if devs is not None:
            try:
                _nd._DISPATCH_DEVICES.update(devs())
            except Exception:
                pass


_seg_vjp_cache = BoundedCache()


def cache_sizes():
    """Current entry counts of the engine's bounded program caches (the
    ``graft_engine_replay_cache_size`` gauge reads these)."""
    return {"replay": len(_replay_cache),
            "infer": len(_infer_cache),
            "seg_vjp": len(_seg_vjp_cache),
            "split": len(_split_cache)}


# ---------------------------------------------------------------------------
# shared flatten/unflatten glue (graftfuse)
# ---------------------------------------------------------------------------
# The bucketed Trainer.step path and the dist kvstore's dtype-grouped
# allreduce both pack many small arrays into one flat buffer and back.
# ONE jitted flattener (jax's jit cache specializes it per signature) and
# one statically-sliced unflatten live here so the packing math exists in
# exactly one place.

@jax.jit
def flatten_arrays(arrs):
    """Concatenate a tuple of arrays into one flat buffer (one dispatch)."""
    return jnp.concatenate([a.reshape(-1) for a in arrs])


def unflatten(flat, shapes):
    """Pure slicing of ``flat`` back into ``shapes`` — static offsets, so
    it traces cleanly inside an outer jit (the fused optimizer programs
    inline it; XLA fuses the slices away)."""
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    offs = np.concatenate([[0], np.cumsum(sizes)]).tolist()
    return tuple(
        jax.lax.slice(flat, (offs[i],), (offs[i + 1],)).reshape(shapes[i])
        for i in range(len(shapes)))


_split_cache = BoundedCache()


def split_flat(flat, shapes):
    """Eager companion of :func:`unflatten`: one cached jitted dispatch
    that splits a flat buffer into per-shape arrays."""
    shapes = tuple(tuple(s) for s in shapes)
    key = (shapes, str(flat.dtype))
    fn = _split_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda f: unflatten(f, shapes))
        _split_cache[key] = fn
    return fn(flat)


def colocate(val, ref):
    """``val`` on ``ref``'s committed device (a no-op when they already
    share one, or when placement cannot be determined).

    The committed-device-safe glue for multi-context replica math: a
    context list like ``[cpu(0) .. cpu(7)]`` commits each replica to its
    own jax device, and jax refuses elementwise ops (and jit calls) that
    mix arrays committed to different devices — so every cross-context
    tree-sum, flat-bucket broadcast and store→replica pull must move the
    operand first.  Transfers preserve bits, so the bit-parity contracts
    of the fused/overlapped step paths are unaffected."""
    try:
        vd = val.devices()
        rd = ref.devices()
    except Exception:
        return val          # tracers / non-jax values carry no placement
    if vd == rd or len(rd) != 1:
        return val
    return jax.device_put(val, next(iter(rd)))
