"""Engine op-bulking: defer eager ops, replay them as ONE XLA program.

The reference's engine amortized per-op push overhead by appending
consecutive eager ops into a bulk segment executed as one engine op
(src/engine/threaded_engine.h:472-509 BulkAppend/BulkFlush,
MXNET_EXEC_BULK_EXEC_*).  The TPU-native analogue: inside a

    with mx.engine.bulk(64):
        for ...:
            eager small ops

scope, pure eager op invocations are RECORDED instead of dispatched; the
pending program is flushed — compiled (once, cached by program shape) and
executed as a single jitted replay — when the scope closes, the segment
reaches ``size`` ops, or any deferred value is materialized (asnumpy,
_read, in-place write, autograd capture).  Steady-state loops hit the
replay cache, so N small ops cost one dispatch (measured ~5x on the
eager micro-benchmark, bench_eager.py).

Out of scope for deferral (dispatched eagerly, exactly as before):
autograd-recording ops (the tape takes jax.vjp at invoke), ``out=``
stores, mutating ops (optimizer updates), sparse storage, ops that
manage their own mesh placement (no_jit), and NaiveEngine mode.  VIEW
creation (reshape/slice) over a deferred value materializes it — views
share storage with their base, which must be concrete for write-through;
keep chains view-free for maximal segments.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["bulk", "flush"]


class _Pending(object):
    """Placeholder for a deferred value (knows shape/dtype for metadata
    queries; ``value`` is filled at flush).  ``owners`` holds weakrefs to
    the NDArrays exposing this value: a pending with no live owner at
    flush time is dead (an intermediate the chain rebound) and is NOT
    returned from the replay program — dead-value elimination keeps the
    per-flush output count at what the user actually kept."""
    __slots__ = ("shape", "dtype", "slot", "value", "state", "epoch",
                 "owners", "error", "__weakref__")

    def __init__(self, shape, dtype, slot, state):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.slot = slot
        self.value = None
        self.state = state
        self.epoch = state.epoch
        self.owners = []
        self.error = None

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n


class _BulkState(object):
    def __init__(self, size):
        self.size = size
        self.epoch = 0           # bumped per flush: "t" refs are only
        #                          valid within their own segment
        self.instructions = []   # (op_name, params, pkey, is_train,
        #                           in_refs, rng_slot, n_out)
        self.ext = []            # concrete jax operands (program inputs)
        self.ext_ids = {}        # id(array) -> slot (identity dedup)
        self.pendings = []       # _Pending objects in slot order

    def add_ext(self, v):
        slot = self.ext_ids.get(id(v))
        if slot is None:
            self.ext.append(v)
            slot = len(self.ext) - 1
            self.ext_ids[id(v)] = slot
        return slot


_tls = threading.local()
_replay_cache = {}
_infer_cache = {}   # (op, input sig, params, train) -> output sig; shape
# inference via jax.eval_shape costs ~a dispatch itself, so recording
# would be slower than executing without this memo


def _current():
    return getattr(_tls, "state", None)


class bulk(object):
    """Context manager: defer up to ``size`` eager ops per segment."""

    def __init__(self, size=64):
        self.size = max(int(size), 1)
        self._prev = None

    def __enter__(self):
        self._prev = _current()
        _tls.state = _BulkState(self.size)
        return self

    def __exit__(self, *exc):
        try:
            flush()
        finally:
            _tls.state = self._prev


def maybe_defer(op, params, vals, is_train, kw):
    """Called from the eager invoke: record the op if a bulk scope is
    active and every input is deferrable.  Returns a tuple of _Pending
    outputs, or None to dispatch eagerly."""
    st = _current()
    if st is None:
        return None
    if len(st.instructions) >= st.size:
        # flush BEFORE recording the next op (never right after one: the
        # freshly created outputs get their owner refs only once invoke
        # wraps them — flushing in between would mis-classify them dead)
        flush()
    from .ops.registry import _hashable
    # stage input refs WITHOUT touching st yet: if we bail (stale
    # pending, failed inference) no orphan ext entries may pollute the
    # replay-cache key
    staged = []
    shapes = []
    for v in vals:
        if type(v) is _Pending:
            if v.state is not st or v.epoch != st.epoch:
                return None       # cross-scope/segment value: materialize
            staged.append(("t", v))
        else:
            staged.append(("e", v))
        shapes.append((tuple(v.shape), str(v.dtype)))
    pkey = _hashable(params)
    ikey = (op.name, tuple(shapes), pkey, bool(is_train))
    out_sig = _infer_cache.get(ikey)
    if out_sig is None:
        try:
            out_sig = op.infer(shapes, params, is_train)
        except Exception:
            return None           # shape inference failed: run eagerly
        _infer_cache[ikey] = out_sig
    in_refs = [(tag, v.slot if tag == "t" else st.add_ext(v))
               for tag, v in staged]
    rng_slot = st.add_ext(kw["rng"]) if "rng" in kw else None
    outs = []
    for shp, dt in out_sig:
        p = _Pending(shp, dt, len(st.pendings), st)
        st.pendings.append(p)
        outs.append(p)
    st.instructions.append((op.name, dict(params), pkey,
                            bool(is_train), tuple(in_refs), rng_slot,
                            len(outs)))
    return tuple(outs)


def resolve(pending):
    """Materialize one deferred value (flushes its segment if needed)."""
    if pending.value is None:
        flush(pending.state)
    if pending.error is not None:
        raise RuntimeError("bulk engine: the deferred segment holding this "
                           "value failed to execute") from pending.error
    if pending.value is None:  # liveness tracking invariant violated
        raise RuntimeError("bulk engine: deferred value was eliminated as "
                           "dead but later read — please report")
    return pending.value


def flush(state=None):
    """Compile (cached) + run the pending segment; fill every _Pending."""
    st = state if state is not None else _current()
    if st is None or not st.instructions:
        return
    instrs = st.instructions
    ext = st.ext
    pendings = st.pendings
    # reset the scope so new ops start a fresh segment (and so re-entrant
    # flushes from _read during execution see an empty program)
    st.instructions, st.ext, st.pendings = [], [], []
    st.ext_ids = {}
    st.epoch += 1

    # only values still exposed through a live NDArray leave the program
    live = tuple(i for i, p in enumerate(pendings)
                 if any(w() is not None for w in p.owners))
    key = (tuple((name, pkey, train, in_refs, rng_slot, n_out)
                 for name, _p, pkey, train, in_refs, rng_slot, n_out
                 in instrs),
           tuple((tuple(v.shape), str(v.dtype)) for v in ext),
           live)
    fn = _replay_cache.get(key)
    if fn is None:
        from .ops.registry import get_op
        plan = [(get_op(name).raw(p, train), in_refs, rng_slot, n_out)
                for name, p, _k, train, in_refs, rng_slot, n_out in instrs]

        def replay(ext_vals):
            tmp = []
            for raw, in_refs, rng_slot, n_out in plan:
                args = [ext_vals[i] if tag == "e" else tmp[i]
                        for tag, i in in_refs]
                kw = {"rng": ext_vals[rng_slot]} if rng_slot is not None \
                    else {}
                res = raw(*args, **kw)
                if not isinstance(res, tuple):
                    res = (res,)
                tmp.extend(res)
            return tuple(tmp[i] for i in live)

        fn = jax.jit(replay)
        _replay_cache[key] = fn
    try:
        results = fn(ext)
    except Exception as exc:
        # stamp every pending with the real cause: later reads raise THIS
        # instead of a misleading liveness error
        for p in pendings:
            p.error = exc
        raise
    for i, v in zip(live, results):
        pendings[i].value = v
    if results:
        # nd.waitall()'s WaitForAll contract covers bulk dispatches too
        from .ndarray import ndarray as _nd
        devs = getattr(results[0], "devices", None)
        if devs is not None:
            try:
                _nd._DISPATCH_DEVICES.update(devs())
            except Exception:
                pass
