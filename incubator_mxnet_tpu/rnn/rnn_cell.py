"""Symbolic RNN cells (ref: python/mxnet/rnn/rnn_cell.py).

Cells compose Symbol graphs step by step; ``unroll`` expands a sequence
into the graph.  Under XLA the unrolled steps compile into one fused
program per bucket length (paired with BucketingModule /
BucketSentenceIter), which is exactly the reference's shared-executor
bucketing story re-expressed as jit specializations.

The Gluon twins are in gluon/rnn/rnn_cell.py; these exist for the legacy
``mx.rnn`` Module workflow.
"""
from __future__ import annotations

from .. import symbol
from ..symbol import Symbol

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams(object):
    """Weight container sharing variables across time steps
    (ref: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.var(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract cell: __call__(inputs, states) → (output, states)
    (ref: rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        """Initial state symbols (ref: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            kw = {} if info is None else {k: v for k, v in info.items()
                                          if not k.startswith("__")}
            kw.update(kwargs)    # caller-provided shape overrides state_info
            state = func(name="%sbegin_state_%d"
                         % (self._prefix, self._init_counter), **kw)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """ref: rnn_cell.py unpack_weights — fused blob → per-gate dict."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """ref: rnn_cell.py pack_weights — per-gate dict → fused blob."""
        from .. import ndarray as nd
        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.concatenate(bias)
        return args

    def _resolve_begin_state(self, states, step_ref):
        """Replace underdetermined zero-states (shape containing 0, i.e.
        batch unknown — what the default ``begin_state()`` produces, as in
        the reference) with states derived from the data symbol.  States
        with concrete shapes pass through untouched."""
        if states is None:
            return self._derived_begin_state(step_ref)
        derived = None
        out = []
        for i, s_ in enumerate(states):
            under = (not s_.is_variable() and s_._op is not None
                     and s_._op.name in ("zeros", "_zeros")
                     and 0 in tuple(s_._params.get("shape", (0,))))
            if under:
                if derived is None:
                    derived = self._derived_begin_state(step_ref)
                out.append(derived[i])
            else:
                out.append(s_)
        return out

    def _derived_begin_state(self, step_ref):
        """Zero states shaped from a per-step (N, C) input symbol.

        The reference leaves batch as 0 in ``sym.zeros((0, H))`` and lets
        NNVM's bidirectional shape inference fill it; our inference is
        forward-only, so the zeros are built *from* the data symbol
        (sum-to-batch + tile), which XLA folds to a constant fill.
        """
        states = []
        for info in self.state_info:
            shape = info["shape"]
            h = shape[-1]
            z2 = symbol.tile(symbol.sum(step_ref * 0, axis=1, keepdims=True),
                             reps=(1, h))                     # (N, H)
            if len(shape) == 3:
                z2 = symbol.tile(symbol.expand_dims(z2, axis=0),
                                 reps=(shape[0], 1, 1))       # (L, N, H)
            states.append(z2)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Expand ``length`` steps into the graph
        (ref: rnn_cell.py BaseRNNCell.unroll)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        states = self._resolve_begin_state(begin_state, inputs[0])
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Slice a (N,T,C) symbol to per-step list, or merge back
    (ref: rnn_cell.py _normalize_sequence)."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, Symbol):
        if merge is False:
            sliced = symbol.split(inputs, axis=in_axis, num_outputs=length,
                                  squeeze_axis=1)
            inputs = [sliced[i] for i in range(length)]
    else:
        assert isinstance(inputs, (list, tuple)) and len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.concat(*inputs, dim=axis)
            in_axis = axis
    if isinstance(inputs, Symbol) and axis != in_axis:
        inputs = symbol.swapaxes(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell h' = act(W·x + R·h + b) (ref: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (ref: rnn_cell.py LSTMCell; gates i, f, c, o)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        split = symbol.split(gates, num_outputs=4, axis=1,
                             name="%sslice" % name)
        in_gate = symbol.Activation(split[0], act_type="sigmoid")
        forget_gate = symbol.Activation(split[1], act_type="sigmoid")
        in_transform = symbol.Activation(split[2], act_type="tanh")
        out_gate = symbol.Activation(split[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (ref: rnn_cell.py GRUCell; gates r, z, o)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(prev_h, weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_s = symbol.split(i2h, num_outputs=3, axis=1)
        h2h_s = symbol.split(h2h, num_outputs=3, axis=1)
        reset = symbol.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = symbol.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_s[2] + reset * h2h_s[2],
                                       act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused RNN over the registered RNN op
    (ref: rnn_cell.py FusedRNNCell → cudnn_rnn).

    The reference packs all weights into one opaque cuDNN blob; here the
    fused op takes the per-layer/direction i2h/h2h arrays directly (named
    like the unfused cells' weights, ``<prefix>l0_i2h_weight`` ...), the
    compute lowers to one lax.scan per layer, and pack/unpack_weights are
    identity — fused and unfused checkpoints share one format by
    construction.
    """

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._weight_vars = []
        dirs = 2 if bidirectional else 1
        prefixes = ["%s%d" % ("lr"[d], l) for l in range(num_layers)
                    for d in range(dirs)]
        for pre in prefixes:
            self._weight_vars.append(self.params.get("%s_i2h_weight" % pre))
            self._weight_vars.append(self.params.get("%s_h2h_weight" % pre))
        for pre in prefixes:
            self._weight_vars.append(self.params.get("%s_i2h_bias" % pre))
            self._weight_vars.append(self.params.get("%s_h2h_bias" % pre))

    @property
    def state_info(self):
        b = self._bidirectional + 1
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def unpack_weights(self, args):
        """Identity — weights already live unfused (see class docstring)."""
        return dict(args)

    def pack_weights(self, args):
        """Identity — weights already live unfused (see class docstring)."""
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:
            # RNN op wants TNC
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        # (N, C) zero reference collapsed over time (TNC axis 0)
        step0 = symbol.sum(inputs * 0, axis=0,
                           name="%sstate_ref" % self._prefix)
        states = list(self._resolve_begin_state(begin_state, step0))
        outputs = symbol.RNN(inputs, *states, *self._weight_vars,
                             state_size=self._num_hidden,
                             num_layers=self._num_layers,
                             bidirectional=self._bidirectional,
                             p=self._dropout, state_outputs=True,
                             mode=self._mode,
                             name="%srnn" % self._prefix)
        out = outputs[0]
        if axis == 1:
            out = symbol.swapaxes(out, dim1=0, dim2=1)
        if merge_outputs is False:
            sliced = symbol.split(out, axis=layout.find("T"),
                                  num_outputs=length, squeeze_axis=1)
            out = [sliced[i] for i in range(length)]
        next_states = ([outputs[1], outputs[2]] if self._mode == "lstm"
                       else [outputs[1]]) if self._get_next_state else []
        return out, next_states

    def __call__(self, inputs, states):
        raise NotImplementedError("FusedRNNCell works on whole sequences; "
                                  "use unroll (ref: rnn_cell.py)")

    def unfuse(self):
        """Equivalent stack of unfused cells (ref: rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells (ref: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            cell._params._params.update(self._params._params)
        self._params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            # None → each child derives zero states from its inputs
            states = None if begin_state is None else begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on outputs (ref: rnn_cell.py DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Wrap a cell, borrowing its params (ref: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        if self.zoneout_outputs > 0:
            m = mask(self.zoneout_outputs, next_output)
            output = symbol.where(m, next_output, prev_output)
        else:
            output = next_output
        if self.zoneout_states > 0:
            states = [symbol.where(mask(self.zoneout_states, ns), ns, s)
                      for ns, s in zip(next_states, states)]
        else:
            states = next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """output = cell(x) + x (ref: rnn_cell.py ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in both directions
    (ref: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._cells = [l_cell, r_cell]
        self._output_prefix = output_prefix
        self._params._params.update(l_cell.params._params)
        self._params._params.update(r_cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def __call__(self, inputs, states):
        raise NotImplementedError("BidirectionalCell cannot be stepped; "
                                  "use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=None if begin_state is None else begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=None if begin_state is None else begin_state[n_l:],
            layout=layout, merge_outputs=False)
        outputs = [symbol.concat(l, r, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l, r) in
                   enumerate(zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs, _ = _normalize_sequence(length, outputs, layout, True)
        return outputs, l_states + r_states
