"""Legacy symbolic RNN API (ref: python/mxnet/rnn/__init__.py).

``mx.rnn`` predates Gluon: cells compose Symbol graphs for use with the
Module/BucketingModule path, with ``BucketSentenceIter`` feeding bucketed
batches.  The Gluon-era cells live in ``mx.gluon.rnn``.
"""
from .rnn_cell import *
from .io import *
from .rnn import *
from . import rnn_cell
from . import io
from . import rnn
