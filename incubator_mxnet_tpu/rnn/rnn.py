"""RNN checkpoint helpers (ref: python/mxnet/rnn/rnn.py).

Fused cells pack gate weights into one blob; these helpers unpack them to
per-gate arrays on save (so checkpoints are portable across fused and
unfused stacks) and re-pack on load.
"""
from __future__ import annotations

from .. import model
from .. import callback as _callback

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _apply_cells(cells, args, fn_name):
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    for cell in cells:
        args = getattr(cell, fn_name)(args)
    return args


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """ref: rnn.py save_rnn_checkpoint — unpack fused weights, then the
    standard model.save_checkpoint."""
    arg_params = _apply_cells(cells, arg_params, "unpack_weights")
    model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """ref: rnn.py load_rnn_checkpoint."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    arg = _apply_cells(cells, arg, "pack_weights")
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (ref: rnn.py do_rnn_checkpoint; cf.
    callback.do_checkpoint)."""
    period = int(max(1, period))

    def _callback_fn(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback_fn
