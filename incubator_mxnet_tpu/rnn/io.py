"""Bucketed sentence iterator (ref: python/mxnet/rnn/io.py).

``BucketSentenceIter`` (:78 in the reference) is the canonical feeder for
``BucketingModule``: sentences are binned by length into buckets, each
batch carries its ``bucket_key`` so the module binds one executor per
bucket — the TPU analogue is one jit specialization per bucket shape
(SURVEY §5.7 long-sequence story).
"""
from __future__ import annotations

import bisect
import logging
import random as pyrandom

import numpy as np

from .. import ndarray as nd
from ..io import DataIter, DataBatch, DataDesc

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Token lists → int lists, growing the vocab for unknown tokens
    (ref: rnn/io.py encode_sentences:30)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
        idx = max(max(vocab.values()) + 1, idx)
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    coded.append(invalid_label)
                    continue
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
                coded.append(vocab[word])
            else:
                coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketing iterator for language modelling: label[t] = data[t+1]
    (ref: rnn/io.py BucketSentenceIter:78)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__()
        if not buckets:
            buckets = [i for i, j in
                       enumerate(np.bincount([len(s) for s in sentences]))
                       if j >= batch_size]
        buckets = sorted(buckets)
        if not buckets:
            raise ValueError("no bucket holds >= batch_size sentences; "
                             "pass buckets= explicitly")
        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        # empty buckets become (0, bucket_len) so downstream 2-D slicing
        # works; they simply contribute no batches
        self.data = [np.asarray(b, dtype=dtype) if b
                     else np.zeros((0, blen), dtype=dtype)
                     for b, blen in zip(self.data, buckets)]
        if ndiscard:
            logging.warning("discarded %d sentences longer than the largest "
                            "bucket", ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)

        if self.major_axis == 0:
            shape = (batch_size, self.default_bucket_key)
        elif self.major_axis == 1:
            shape = (self.default_bucket_key, batch_size)
        else:
            raise ValueError("invalid layout %s: must contain N" % layout)
        self.provide_data = [DataDesc(data_name, shape, dtype,
                                      layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, dtype,
                                       layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1, batch_size))
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        """Shuffle buckets and sentences within each (ref: io.py reset)."""
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck, dtype=self.dtype))
            self.ndlabel.append(nd.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        return DataBatch([data], [label], pad=0,
                         bucket_key=self.buckets[i],
                         provide_data=[DataDesc(self.data_name, data.shape,
                                                self.dtype,
                                                layout=self.layout)],
                         provide_label=[DataDesc(self.label_name, label.shape,
                                                 self.dtype,
                                                 layout=self.layout)])
