"""Elementwise / broadcast / reduce / linalg operators.

TPU-native equivalents of src/operator/tensor/elemwise_binary_broadcast_op*,
elemwise_unary_op*, broadcast_reduce_op*, dot*.{cc,cu} (reference, SURVEY
§2.2).  Every op is a pure jnp/lax function; XLA fuses elementwise chains
into matmul epilogues (the job MXNet's engine bulking + mshadow expression
templates did by hand).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# binary broadcast family (reference: elemwise_binary_broadcast_op_basic.cc)
# ---------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
}
_BINARY_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}

_ALIASES = {
    "broadcast_add": ("elemwise_add", "_plus", "_add"),
    "broadcast_sub": ("elemwise_sub", "_minus", "_sub"),
    "broadcast_mul": ("elemwise_mul", "_mul"),
    "broadcast_div": ("elemwise_div", "_div"),
    "broadcast_mod": ("_mod",),
    "broadcast_power": ("_power", "_pow"),
    "broadcast_maximum": ("_maximum",),
    "broadcast_minimum": ("_minimum",),
    "broadcast_hypot": ("_hypot",),
    "broadcast_equal": ("_equal",),
    "broadcast_not_equal": ("_not_equal",),
    "broadcast_greater": ("_greater",),
    "broadcast_greater_equal": ("_greater_equal",),
    "broadcast_lesser": ("_lesser",),
    "broadcast_lesser_equal": ("_lesser_equal",),
    "broadcast_logical_and": ("_logical_and",),
    "broadcast_logical_or": ("_logical_or",),
    "broadcast_logical_xor": ("_logical_xor",),
}


def _reg_binary(name, fn, differentiable=True, cast=None):
    def fcompute(lhs, rhs, _fn=fn, _cast=cast):
        out = _fn(lhs, rhs)
        if _cast:
            out = out.astype(lhs.dtype)
        return out
    fcompute.__doc__ = "Broadcasting binary op %s (ref: src/operator/tensor/elemwise_binary_broadcast_op*.cc)" % name
    register(name, num_inputs=2, differentiable=differentiable,
             aliases=_ALIASES.get(name, ()))(fcompute)


for _n, _f in _BINARY.items():
    _reg_binary(_n, _f)
for _n, _f in _BINARY_CMP.items():
    # MXNet comparison ops return same-dtype 0/1 arrays, not bools.
    _reg_binary(_n, _f, differentiable=False, cast=True)

# ---------------------------------------------------------------------------
# scalar ops (reference: elemwise_binary_scalar_op_basic.cc)
# ---------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
}
_SCALAR_CMP = {
    "_equal_scalar": lambda x, s: (x == s),
    "_not_equal_scalar": lambda x, s: (x != s),
    "_greater_scalar": lambda x, s: (x > s),
    "_greater_equal_scalar": lambda x, s: (x >= s),
    "_lesser_scalar": lambda x, s: (x < s),
    "_lesser_equal_scalar": lambda x, s: (x <= s),
}

for _n, _f in _SCALAR.items():
    def _sc(data, scalar=0.0, _fn=_f):
        return _fn(data, jnp.asarray(scalar, data.dtype))
    _sc.__doc__ = "Scalar op %s (ref: elemwise_binary_scalar_op_basic.cc)" % _n
    register(_n, num_inputs=1)(_sc)

for _n, _f in _SCALAR_CMP.items():
    def _sc(data, scalar=0.0, _fn=_f):
        return _fn(data, scalar).astype(data.dtype)
    _sc.__doc__ = "Scalar comparison %s" % _n
    register(_n, num_inputs=1, differentiable=False)(_sc)

# ---------------------------------------------------------------------------
# unary family (reference: elemwise_unary_op_basic.cc, mshadow_op.h)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "erf": jax.scipy.special.erf,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
}
_UNARY_NODIFF = {
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}

_UNARY_ALIASES = {"negative": ("_np_negative",), "abs": ("_np_abs",)}

for _n, _f in _UNARY.items():
    def _un(data, _fn=_f):
        return _fn(data)
    _un.__doc__ = "Unary op %s (ref: src/operator/tensor/elemwise_unary_op_basic.cc, mshadow_op.h)" % _n
    register(_n, num_inputs=1, aliases=_UNARY_ALIASES.get(_n, ()))(_un)

for _n, _f in _UNARY_NODIFF.items():
    def _un(data, _fn=_f):
        return _fn(data)
    _un.__doc__ = "Unary (zero-grad) op %s" % _n
    register(_n, num_inputs=1, differentiable=False)(_un)


@register("clip", num_inputs=1)
def _clip(data, a_min=0.0, a_max=1.0):
    """Clip values (ref: src/operator/tensor/matrix_op.cc Clip)."""
    return jnp.clip(data, a_min, a_max)


@register("add_n", num_inputs=None, aliases=("ElementWiseSum", "elemwise_sum", "_sum"))
def _add_n(*args):
    """Sum of N arrays (ref: src/operator/tensor/elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out

# ---------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        ax = tuple(range(ndim))
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _reg_reduce(name, jfn, differentiable=True, aliases=()):
    def fcompute(data, axis=None, keepdims=False, exclude=False, _fn=jfn):
        ax = _norm_axis(axis, data.ndim, exclude)
        return _fn(data, axis=ax, keepdims=keepdims)
    fcompute.__doc__ = "Reduction %s (ref: src/operator/tensor/broadcast_reduce_op_value.cc)" % name
    register(name, num_inputs=1, differentiable=differentiable, aliases=aliases)(fcompute)


_reg_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reg_reduce("mean", jnp.mean)
_reg_reduce("prod", jnp.prod)
_reg_reduce("nansum", jnp.nansum)
_reg_reduce("nanprod", jnp.nanprod)
_reg_reduce("max", jnp.max, aliases=("max_axis",))
_reg_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm", num_inputs=1)
def _norm(data, ord=2, axis=None, keepdims=False):
    """L2 (or L1) norm (ref: broadcast_reduce_op_value.cc L2Norm)."""
    ax = None if axis is None else _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims))


@register("argmax", num_inputs=1, differentiable=False)
def _argmax(data, axis=None, keepdims=False):
    """ref: broadcast_reduce_op_index.cc. Returns float dtype like MXNet."""
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", num_inputs=1, differentiable=False)
def _argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmax_channel", num_inputs=1, differentiable=False)
def _argmax_channel(data):
    """argmax over axis 1 (ref: broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)

# ---------------------------------------------------------------------------
# broadcast helpers
# ---------------------------------------------------------------------------


@register("broadcast_to", num_inputs=1)
def _broadcast_to(data, shape=()):
    """ref: broadcast_reduce_op_value.cc BroadcastTo (0 = keep dim)."""
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_axis", num_inputs=1, aliases=("broadcast_axes",))
def _broadcast_axis(data, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))

# ---------------------------------------------------------------------------
# dot / batch_dot (MXU territory; reference: src/operator/tensor/dot-inl.h)
# ---------------------------------------------------------------------------


@register("dot", num_inputs=2)
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Matrix/tensor product on the MXU (ref: dot-inl.h).

    2-D×2-D → matmul; >2-D follows MXNet: reshape lhs to (-1, last) and rhs
    to (first, -1).  bf16/f32 inputs hit the systolic array directly.
    """
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    a2 = a.reshape((-1, a.shape[-1]))
    b2 = b.reshape((b.shape[0], -1))
    out = jnp.dot(a2, b2, preferred_element_type=jnp.promote_types(a.dtype, b.dtype))
    return out.reshape(a.shape[:-1] + b.shape[1:])


@register("batch_dot", num_inputs=2)
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul (ref: dot-inl.h BatchDot)."""
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("khatri_rao", num_inputs=None)
def _khatri_rao(*mats):
    """Column-wise Khatri-Rao product (ref: src/operator/contrib/krprod.h)."""
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, out.shape[1])
    return out


# mxnet exposes L2 normalization as an op
@register("L2Normalization", num_inputs=1)
def _l2norm(data, eps=1e-10, mode="instance"):
    """ref: src/operator/l2_normalization.cc"""
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / n


@register("smooth_l1", num_inputs=1)
def _smooth_l1(data, scalar=1.0):
    """ref: src/operator/tensor/elemwise_binary_scalar_op_extended.cc"""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * jnp.square(data), absd - 0.5 / s2)
