"""Advanced linear-algebra operators (the LAPACK ``la_op`` family) + FFT.

TPU-native equivalents of the reference's LAPACK-backed operator family
(src/operator/tensor/la_op.cc — _linalg_gemm/gemm2/potrf/potri/trmm/trsm/
sumlogdiag/syrk/gelqf/syevd) and the cuFFT contrib ops
(src/operator/contrib/fft.cc, ifft.cc) plus count_sketch
(src/operator/contrib/count_sketch.cc).

Design: where the reference binds cuSOLVER/LAPACK routines per matrix and
loops over the batch, here every op is a batched ``jax.lax.linalg`` /
``jnp.linalg`` call over the last two axes — XLA lowers these to blocked
MXU-friendly kernels and batches natively, and every op is reverse-mode
differentiable through JAX's decomposition JVP rules (no hand-written
_backward_linalg_* twin ops needed).

All ops operate on stacks of matrices: input ``(..., m, n)``; leading axes
are batch.  Triangular ops read only the lower triangle of ``A`` (BLAS
``trmm``/``trsm`` semantics — the strict upper part is ignored, as in the
reference).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _t(x):
    """Transpose the trailing two axes of a matrix stack."""
    return jnp.swapaxes(x, -1, -2)


def _op(a, transpose):
    return _t(a) if transpose else a


def _tri_solve(a, b, *, transpose=False, rightside=False, lower=True):
    """Batched triangular solve: op(a) @ x = b (or x @ op(a) = b)."""
    return lax.linalg.triangular_solve(
        a, b, left_side=not rightside, lower=lower,
        transpose_a=transpose)


# ---------------------------------------------------------------------------
# la_op family (ref: src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------


@register("_linalg_gemm", num_inputs=3, input_names=("A", "B", "C"))
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False,
                 alpha=1.0, beta=1.0):
    """out = alpha * op(A) @ op(B) + beta * C.

    ref: src/operator/tensor/la_op.cc:36 (_linalg_gemm, LaMatrixMacParam).
    """
    return alpha * jnp.matmul(_op(A, transpose_a), _op(B, transpose_b)) + beta * C


@register("_linalg_gemm2", num_inputs=2, input_names=("A", "B"))
def _linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    """out = alpha * op(A) @ op(B).

    ref: src/operator/tensor/la_op.cc:97 (_linalg_gemm2, LaMatrixMultParam).
    """
    return alpha * jnp.matmul(_op(A, transpose_a), _op(B, transpose_b))


@register("_linalg_potrf", num_inputs=1, input_names=("A",))
def _linalg_potrf(A):
    """Cholesky factorization: A = L @ L.T, returns lower-triangular L.

    ref: src/operator/tensor/la_op.cc:153 (_linalg_potrf).
    """
    return lax.linalg.cholesky(A)


@register("_linalg_potri", num_inputs=1, input_names=("A",))
def _linalg_potri(A):
    """Matrix inverse from a Cholesky factor: in = L, out = (L @ L.T)^-1.

    Computed as Linv.T @ Linv with Linv from a batched triangular solve
    (the reference calls LAPACK potri: src/operator/tensor/la_op.cc:202).
    """
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = _tri_solve(A, eye)
    return jnp.matmul(_t(linv), linv)


@register("_linalg_trmm", num_inputs=2, input_names=("A", "B"))
def _linalg_trmm(A, B, transpose=False, rightside=False, alpha=1.0):
    """Multiplication with a lower-triangular matrix.

    out = alpha * op(tril(A)) @ B   (or  alpha * B @ op(tril(A)) if rightside).
    ref: src/operator/tensor/la_op.cc:257 (_linalg_trmm, LaTriangMatrixMultParam).
    """
    L = _op(jnp.tril(A), transpose)
    out = jnp.matmul(B, L) if rightside else jnp.matmul(L, B)
    return alpha * out


@register("_linalg_trsm", num_inputs=2, input_names=("A", "B"))
def _linalg_trsm(A, B, transpose=False, rightside=False, alpha=1.0):
    """Solve op(tril(A)) @ X = alpha*B  (or X @ op(tril(A)) = alpha*B).

    ref: src/operator/tensor/la_op.cc:320 (_linalg_trsm).
    """
    return _tri_solve(A, alpha * B, transpose=transpose, rightside=rightside)


@register("_linalg_sumlogdiag", num_inputs=1, input_names=("A",))
def _linalg_sumlogdiag(A):
    """Sum of log of the diagonal elements of each square matrix in the stack.

    ref: src/operator/tensor/la_op.cc:383 (_linalg_sumlogdiag).
    """
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("_linalg_syrk", num_inputs=1, input_names=("A",))
def _linalg_syrk(A, transpose=False, alpha=1.0):
    """Symmetric rank-k: alpha * A @ A.T (or alpha * A.T @ A when transpose).

    ref: src/operator/tensor/la_op.cc:426 (_linalg_syrk, LaSyrkParam).
    """
    a = _op(A, transpose)
    return alpha * jnp.matmul(a, _t(a))


@register("_linalg_gelqf", num_inputs=1, num_outputs=2, input_names=("A",))
def _linalg_gelqf(A):
    """LQ factorization of a full-rank (m, n) matrix with m <= n: A = L @ Q.

    Returns (Q, L): Q with orthonormal rows (m, n), L lower-triangular (m, m).
    Built from the QR of A.T (A.T = Qc @ R  =>  A = R.T @ Qc.T), the TPU-native
    route — XLA has a blocked QR; LAPACK gelqf is just its mirror image.
    ref: src/operator/tensor/la_op.cc:483 (_linalg_gelqf).
    """
    q, r = lax.linalg.qr(_t(A), full_matrices=False)
    # Normalize sign so L has a non-negative diagonal (LAPACK convention up
    # to sign; this makes the factorization deterministic).
    d = jnp.sign(jnp.diagonal(r, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, jnp.ones_like(d), d)
    q = q * d[..., None, :]
    r = r * d[..., :, None]
    return _t(q), _t(r)


@register("_linalg_syevd", num_inputs=1, num_outputs=2, input_names=("A",))
def _linalg_syevd(A):
    """Symmetric eigendecomposition: A = U.T @ diag(L) @ U.

    Returns (U, L); eigenvectors are the *rows* of U, eigenvalues L ascending
    (matching the reference's LAPACK syevd row convention,
    src/operator/tensor/la_op.cc:554).
    """
    v, w = lax.linalg.eigh(A)  # lax.linalg.eigh: eigenvectors first
    return _t(v), w


# ---------------------------------------------------------------------------
# FFT / IFFT (ref: src/operator/contrib/fft.cc, ifft.cc)
# ---------------------------------------------------------------------------


@register("_contrib_fft", num_inputs=1, input_names=("data",))
def _contrib_fft(data, compute_size=128):
    """1D FFT over the last axis of a real input.

    Input (..., d) real; output (..., 2*d) interleaved [re0, im0, re1, im1...]
    — the reference's cuFFT C2C layout (src/operator/contrib/fft.cc:43).
    ``compute_size`` (the reference's sub-batch size for cuFFT plans) is
    accepted for parity; XLA batches the transform natively.
    """
    del compute_size
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([jnp.real(c), jnp.imag(c)], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register("_contrib_ifft", num_inputs=1, input_names=("data",))
def _contrib_ifft(data, compute_size=128):
    """Unnormalized 1D inverse FFT of interleaved complex input.

    Input (..., 2*k) as [re, im, ...]; output (..., k), real part only.
    Matches cuFFT's unnormalized CUFFT_INVERSE (no 1/N factor — the
    reference leaves rescaling to the caller, src/operator/contrib/ifft.cc:44).
    """
    del compute_size
    x = data.reshape(data.shape[:-1] + (data.shape[-1] // 2, 2)).astype(jnp.float32)
    c = lax.complex(x[..., 0], x[..., 1])
    k = c.shape[-1]
    return (jnp.real(jnp.fft.ifft(c, axis=-1)) * k).astype(data.dtype)


@register("_contrib_count_sketch", num_inputs=3, input_names=("data", "h", "s"),
          nograd_inputs=(1, 2))
def _contrib_count_sketch(data, h, s, out_dim, processing_batch_size=32):
    """Count-sketch projection: map d-dim rows to out_dim-dim rows.

    out[n, h[i]] += s[i] * data[n, i] — the tensor-sketch primitive
    (ref: src/operator/contrib/count_sketch.cc:45).  ``h`` (bucket index,
    ints in [0, out_dim)) and ``s`` (signs ±1) broadcast against data's
    row dimension.  ``processing_batch_size`` accepted for parity.
    """
    del processing_batch_size
    d = data.shape[-1]
    lead = data.shape[:-1]
    flat = data.reshape((-1, d))
    hb = jnp.broadcast_to(h.astype(jnp.int32).reshape((-1, d))[0], (d,))
    sb = jnp.broadcast_to(s.reshape((-1, d))[0], (d,)).astype(data.dtype)
    out = jnp.zeros((flat.shape[0], int(out_dim)), dtype=data.dtype)
    out = out.at[:, hb].add(flat * sb)
    return out.reshape(lead + (int(out_dim),))
