"""Single operator registry serving both execution modes.

This is the TPU-native rebirth of the reference's NNVM op registry
(src/operator/*, NNVM_REGISTER_OP; include/mxnet/op_attr_types.h): ONE
registration per operator feeds

  * the eager NDArray front-end  (reference: src/imperative/imperative.cc:86)
  * the autograd tape            (reference: src/imperative/imperative.cc:182)
  * the symbolic graph executor  (reference: src/executor/graph_executor.cc)

Differences from the reference, by design (SURVEY §7):

  * ``fcompute`` is a pure JAX function — XLA is the kernel library, Pallas
    the escape hatch — instead of per-device FCompute<cpu|gpu> pairs.
  * There are no hand-written FInferShape/FInferType attributes: shape and
    dtype inference is ``jax.eval_shape`` over the same fcompute, so the two
    can never disagree (reference needed 363 files of paired infer+compute).
  * There is no FGradient twin-op: gradients come from ``jax.vjp`` over the
    same fcompute (the tape stores the vjp closure).
  * Scheduling/async: each eager call dispatches through a cached
    ``jax.jit``; XLA's async dispatch + donation plays the role of the
    ThreadedEngine (src/engine/threaded_engine.cc) — ops are issued without
    blocking Python and dependencies resolve in data-flow order on device.
"""
from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional

import jax

__all__ = ["Operator", "register", "get_op", "list_ops", "alias",
           "registration_log"]

_REGISTRY: dict[str, "Operator"] = {}

# Every register()/alias() call appends one entry here so static analysis
# (analysis/graftlint) can see registration ORDER and collisions — the
# dict alone silently keeps only the last binding per name.  Entries:
# {"name", "op", "alias_of" (canonical name or None), "file", "line",
#  "collided_with" (the Operator this binding displaced, or None)}.
_REGISTRATION_LOG: list[dict] = []


def _source_of(fcompute):
    """(file, line) of an fcompute, or (None, None) for C callables."""
    code = getattr(fcompute, "__code__", None)
    if code is None:
        return None, None
    return code.co_filename, code.co_firstlineno


def _log_registration(name, op, alias_of=None):
    prev = _REGISTRY.get(name)
    fname, line = _source_of(op.fcompute)
    _REGISTRATION_LOG.append({
        "name": name, "op": op, "alias_of": alias_of,
        "file": fname, "line": line,
        "collided_with": prev if (prev is not None and prev is not op)
        else None,
    })


def registration_log():
    """The append-only log of every registration (canonical + alias)."""
    return list(_REGISTRATION_LOG)


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


class Operator:
    """One registered operator.

    Parameters
    ----------
    name : canonical op name (e.g. ``Convolution``, ``broadcast_add``).
    fcompute : pure function ``(*inputs, **params) -> array | tuple``.
        If ``needs_rng``, it must accept a keyword ``rng`` (a jax PRNG key).
        If ``takes_is_train``, it must accept keyword ``is_train`` (static).
    num_inputs : fixed arity, or ``None`` for variadic (e.g. ``concat``).
    num_outputs : number of outputs produced by fcompute.
    num_visible_outputs : outputs exposed to the user (extra outputs are
        auxiliary, e.g. BatchNorm's batch mean/var); defaults to num_outputs.
    differentiable : whether vjp should be recorded on the tape.
    nograd_inputs : indices of inputs that never receive gradient
        (e.g. integer indices of ``take``).
    """

    def __init__(self, name: str, fcompute: Callable, *, num_inputs: Optional[int] = 1,
                 num_outputs: int = 1, num_visible_outputs: Optional[int] = None,
                 differentiable: bool = True, needs_rng: bool = False,
                 takes_is_train: bool = False, nograd_inputs=(), mutate_inputs=(),
                 input_names=None, aux_input_names=(), fargnames=None,
                 finfer_params=None, fvisible=None, fnum_outputs=None,
                 no_jit: bool = False, doc: str = ""):
        self.name = name
        self.fcompute = fcompute
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.num_visible_outputs = (num_outputs if num_visible_outputs is None
                                    else num_visible_outputs)
        self.differentiable = differentiable
        self.needs_rng = needs_rng
        self.takes_is_train = takes_is_train
        self.nograd_inputs = tuple(nograd_inputs)
        self.mutate_inputs = tuple(mutate_inputs)
        self.input_names = input_names
        self.aux_input_names = tuple(aux_input_names)
        self.fargnames = fargnames
        self.finfer_params = finfer_params
        self.fvisible = fvisible
        self.fnum_outputs = fnum_outputs   # params → output count (split etc.)
        self.no_jit = no_jit   # ops that manage their own device placement
        # (multi-device shard_map bodies): the eager micro-jit would pin
        # them to the default device and clash with the op's mesh
        self.doc = doc
        self._jit_cache: dict = {}
        # Populated EAGERLY so registry introspection (graftlint, symbol
        # executors) never mutates Operator instances mid-flight — the
        # lazy first-call cache made concurrent readers race on attribute
        # creation and made linting observable as a state change.  The
        # __defaults__ fast path keeps default-free throwaway Operators
        # (the per-flush _BulkSegment lambda, engine.py) off
        # inspect.signature entirely.
        if getattr(fcompute, "__defaults__", None) \
                or getattr(fcompute, "__kwdefaults__", None):
            try:
                sig = inspect.signature(fcompute)
                self._defaults = {k: v.default
                                  for k, v in sig.parameters.items()
                                  if v.default is not inspect.Parameter.empty}
            except (TypeError, ValueError):
                self._defaults = {}
        else:
            self._defaults = {}

    def arg_names(self, params: dict):
        """Required input names given static params, or None if unnamed
        (parity: FListInputNames, which ConvolutionParam et al. vary by
        no_bias — include/mxnet/op_attr_types.h). Falls back to the
        fcompute's own default for no_bias (Deconvolution defaults True)."""
        if self.fargnames is not None:
            return list(self.fargnames(params))
        if self.input_names is None:
            return None
        names = list(self.input_names)
        if "bias" in names:
            no_bias = params.get("no_bias", self._param_default("no_bias"))
            if no_bias:
                names.remove("bias")
        return names

    def _param_default(self, pname):
        return self._defaults.get(pname)

    def contract(self):
        """Machine-readable registration contract for static analysis.

        Everything the op promised at registration time, in plain data —
        analysis/graftlint verifies these promises against the fcompute
        signature and body without importing anything op-specific."""
        fname, line = _source_of(self.fcompute)
        return {
            "name": self.name,
            "num_inputs": self.num_inputs,
            "num_outputs": self.num_outputs,
            "num_visible_outputs": self.num_visible_outputs,
            "differentiable": self.differentiable,
            "needs_rng": self.needs_rng,
            "takes_is_train": self.takes_is_train,
            "nograd_inputs": list(self.nograd_inputs),
            "mutate_inputs": list(self.mutate_inputs),
            "input_names": (None if self.input_names is None
                            else list(self.input_names)),
            "aux_input_names": list(self.aux_input_names),
            "has_fargnames": self.fargnames is not None,
            "has_finfer_params": self.finfer_params is not None,
            "has_fvisible": self.fvisible is not None,
            "has_fnum_outputs": self.fnum_outputs is not None,
            "no_jit": self.no_jit,
            "param_defaults": dict(self._defaults),
            "source_file": fname,
            "source_line": line,
        }

    def visible_outputs(self, params: dict, n_outputs: int) -> int:
        """How many of ``n_outputs`` are user-visible (rest are aux, e.g.
        BatchNorm batch stats unless output_mean_var)."""
        if self.fvisible is not None:
            return self.fvisible(params, n_outputs)
        return n_outputs - (self.num_outputs - self.num_visible_outputs)

    # ---- compiled dispatch -------------------------------------------------
    def bind(self, params: dict, is_train: bool = False):
        """Return the cached jitted callable for this (params, is_train) combo.

        The returned callable takes the op's array inputs positionally (plus
        ``rng=`` if needs_rng).  This cache is the analogue of the reference's
        CachedOp / engine op-bulking: steady-state eager calls are a dict hit
        + an XLA async dispatch.
        """
        if self.no_jit:
            return self.raw(params, is_train)
        key = (_hashable(params), bool(is_train))
        fn = self._jit_cache.get(key)
        if fn is None:
            kw = dict(params)
            if self.takes_is_train:
                kw["is_train"] = bool(is_train)
            raw = functools.partial(self.fcompute, **kw)
            fn = jax.jit(raw)
            self._jit_cache[key] = fn
        return fn

    def raw(self, params: dict, is_train: bool = False):
        """Un-jitted closure (used when tracing inside an outer jit)."""
        kw = dict(params)
        if self.takes_is_train:
            kw["is_train"] = bool(is_train)
        return functools.partial(self.fcompute, **kw)

    def infer(self, input_shapes_dtypes, params: dict, is_train: bool = False):
        """Shape/dtype inference via jax.eval_shape (replaces FInferShape/Type)."""
        structs = [jax.ShapeDtypeStruct(s, d) for (s, d) in input_shapes_dtypes]
        fn = self.raw(params, is_train)
        if self.needs_rng:
            out = jax.eval_shape(functools.partial(fn, rng=jax.ShapeDtypeStruct((2,), "uint32")), *structs)
        else:
            out = jax.eval_shape(fn, *structs)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return [(tuple(o.shape), o.dtype) for o in out]

    def __repr__(self):
        return "Operator(%s)" % self.name


def register(name, **kwargs):
    """Decorator: register ``fcompute`` under ``name`` (+ optional aliases)."""
    aliases = kwargs.pop("aliases", ())

    def dec(fcompute):
        op = Operator(name, fcompute, doc=fcompute.__doc__ or "", **kwargs)
        _log_registration(name, op)
        _REGISTRY[name] = op
        for a in aliases:
            _log_registration(a, op, alias_of=name)
            _REGISTRY[a] = op
        return fcompute

    return dec


def alias(existing, *names):
    op = _REGISTRY[existing]
    for n in names:
        _log_registration(n, op, alias_of=existing)
        _REGISTRY[n] = op


def get_op(name: str) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError("Operator %r is not registered (have %d ops)"
                       % (name, len(_REGISTRY))) from None


def list_ops():
    return sorted(_REGISTRY)
