"""INT8 quantization operator family.

TPU-native rebirth of src/operator/quantization/ (19 files):

* ``_contrib_quantize`` / ``_contrib_dequantize`` / ``_contrib_requantize``
  (ref: quantize-inl.h, dequantize-inl.h, requantize-inl.h) — the same
  zero-centered int8 / affine uint8 schemes, as pure XLA element-wise code.
* ``_contrib_quantized_conv`` / ``_contrib_quantized_fully_connected``
  (ref: quantized_conv.cc, quantized_fully_connected.cc) — int8×int8→int32
  compute.  Where the reference calls cuDNN's int8 conv (quantized_conv.cu),
  we hand XLA int8 operands with ``preferred_element_type=int32`` so the
  contraction runs natively on the MXU's int8 path — this is the op family
  TPUs were built for.
* ``_contrib_quantized_pooling`` / ``_contrib_quantized_flatten``
  (ref: quantized_pooling.cc, quantized_flatten.cc) — shape/window ops that
  stay in int8 and carry the (min, max) range through unchanged.

Range convention (identical to the reference's quantization_utils.h):
every quantized tensor travels as a triple ``(q, min_range, max_range)``
where min/max are float32 scalars giving the real-valued range that the
integer grid spans.  int8 is always zero-centered: the effective range is
``[-r, r]`` with ``r = max(|min|, |max|)`` and scale ``127/r``.

All ops here are inference-only (non-differentiable), as in the reference
(quantization is applied to a trained model by the graph pass in
contrib/quantization.py).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from .registry import register
from .nn import _pooling, _conv_param_shapes, _fc_param_shapes

_RANGE_NAMES = ("min_data", "max_data", "min_weight", "max_weight",
                "min_bias", "max_bias")


def _qconv_param_shapes(data_shape, params):
    d = _conv_param_shapes(data_shape, params)
    d.update({n: () for n in _RANGE_NAMES})
    return d


def _qfc_param_shapes(data_shape, params):
    d = _fc_param_shapes(data_shape, params)
    d.update({n: () for n in _RANGE_NAMES})
    return d

INT8_MAX = 127.0
UINT8_MAX = 255.0


def _real_range(min_r, max_r):
    """Zero-centered effective range r such that int8 grid covers [-r, r].
    Floored at a tiny epsilon so all-zero tensors quantize to 0, not NaN."""
    return jnp.maximum(jnp.maximum(jnp.abs(min_r), jnp.abs(max_r)),
                       jnp.float32(1e-30))


def _quantize_int8(x, min_r, max_r):
    r = _real_range(min_r, max_r)
    scale = INT8_MAX / r
    q = jnp.clip(jnp.rint(x.astype(jnp.float32) * scale), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), -r, r


@register("_contrib_quantize", num_inputs=3, num_outputs=3,
          input_names=("data", "min_range", "max_range"),
          differentiable=False)
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Quantize float → int8/uint8 given the real range of the values.

    ref: quantize-inl.h quantize_zero_centered (int8) /
    quantize_unsigned (uint8).  Returns (quantized, out_min, out_max).
    """
    min_r = jnp.asarray(min_range, jnp.float32).reshape(())
    max_r = jnp.asarray(max_range, jnp.float32).reshape(())
    if out_type == "int8":
        q, omin, omax = _quantize_int8(data, min_r, max_r)
        return q, jnp.float32(1) * omin, jnp.float32(1) * omax
    if out_type == "uint8":
        scale = UINT8_MAX / jnp.maximum(max_r - min_r, jnp.float32(1e-30))
        q = jnp.clip(jnp.rint((data.astype(jnp.float32) - min_r) * scale),
                     0.0, UINT8_MAX).astype(jnp.uint8)
        return q, min_r, max_r
    raise ValueError("out_type must be int8 or uint8, got %r" % out_type)


@register("_contrib_dequantize", num_inputs=3, num_outputs=1,
          input_names=("data", "min_range", "max_range"),
          differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32"):
    """int8/uint8 → float32 (ref: dequantize-inl.h)."""
    min_r = jnp.asarray(min_range, jnp.float32).reshape(())
    max_r = jnp.asarray(max_range, jnp.float32).reshape(())
    if data.dtype == jnp.int8:
        r = _real_range(min_r, max_r)
        return data.astype(jnp.float32) * (r / INT8_MAX)
    if data.dtype == jnp.uint8:
        return data.astype(jnp.float32) * ((max_r - min_r) / UINT8_MAX) + min_r
    # int32 accumulators (out of quantized conv/fc before requantize)
    r = _real_range(min_r, max_r)
    return data.astype(jnp.float32) * (r / float(np.iinfo(np.int32).max))


@register("_contrib_requantize", num_inputs=3, num_outputs=3,
          input_names=("data", "min_range", "max_range"),
          differentiable=False)
def _requantize(data, min_range, max_range,
                min_calib_range=None, max_calib_range=None):
    """int32 accumulator → int8 with a narrower range (ref: requantize-inl.h).

    With a calibrated range (set by the graph pass after calibration) the
    rescale factor is static; without one the range is computed from the
    data at runtime (the reference's "calib_mode=none" slow path).
    """
    min_r = jnp.asarray(min_range, jnp.float32).reshape(())
    max_r = jnp.asarray(max_range, jnp.float32).reshape(())
    # real value of one int32 step in the accumulator
    in_scale = _real_range(min_r, max_r) / float(np.iinfo(np.int32).max)
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        out_r = max(abs(float(min_calib_range)), abs(float(max_calib_range)))
        q = jnp.clip(jnp.rint(real * (INT8_MAX / out_r)), -INT8_MAX, INT8_MAX)
        return (q.astype(jnp.int8), jnp.float32(-out_r), jnp.float32(out_r))
    out_r = jnp.maximum(jnp.max(jnp.abs(real)), jnp.float32(1e-30))
    q = jnp.clip(jnp.rint(real * (INT8_MAX / out_r)), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), -out_r, out_r


def _int32_range(range_a, range_b):
    """Output (min, max) of an int8×int8→int32 contraction: one int32 step
    represents (ra/127)·(rb/127) real units, scaled so the int32 extremes
    map to ±ra·rb·(2^31-1)/127² (ref: quantization_utils.h
    QuantizationRangeForMultiplication)."""
    r = range_a * range_b * (float(np.iinfo(np.int32).max) / (INT8_MAX * INT8_MAX))
    return -r, r


def _q_argnames(params):
    """Input names for quantized conv/FC: data tensors then range scalars
    (ref: quantized_conv.cc FListInputNames order data..., min1, max1, ...)."""
    if params.get("no_bias", True):
        return ("data", "weight", "min_data", "max_data",
                "min_weight", "max_weight")
    return ("data", "weight", "bias", "min_data", "max_data",
            "min_weight", "max_weight", "min_bias", "max_bias")


def _rescale_bias_to_acc(bias, min_b, max_b, acc_max):
    """Re-express an int8 bias on the int32-accumulator grid: one int32 unit
    is acc_max/(2^31-1) real units (ref: quantized_conv.cc bias handling)."""
    rb = _real_range(jnp.asarray(min_b, jnp.float32).reshape(()),
                     jnp.asarray(max_b, jnp.float32).reshape(()))
    acc_step = acc_max / float(np.iinfo(np.int32).max)
    bias_real = bias.astype(jnp.float32) * (rb / INT8_MAX)
    return jnp.rint(bias_real / acc_step).astype(jnp.int32)


@register("_contrib_quantized_conv", num_inputs=None, num_outputs=3,
          fargnames=_q_argnames, finfer_params=_qconv_param_shapes,
          differentiable=False)
def _quantized_conv(*args, kernel=(), stride=(), dilate=(), pad=(),
                    num_filter=0, num_group=1, no_bias=True, workspace=1024,
                    cudnn_tune=None, cudnn_off=False, layout=None):
    """int8 convolution with int32 accumulation (ref: quantized_conv.cc).

    The conv itself is the float Convolution fcompute handed int8 operands —
    XLA lowers an s8×s8→s32 conv straight onto the MXU int8 pipeline, the
    TPU-native replacement for the reference's cuDNN int8 path.
    """
    if no_bias:
        data, weight, min_d, max_d, min_w, max_w = args
        bias = None
    else:
        data, weight, bias, min_d, max_d, min_w, max_w, min_b, max_b = args
    nd_ = len(kernel) if kernel else data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    spatial = "".join("DHW"[3 - nd_ + i] for i in range(nd_))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)   # s8×s8→s32 on the MXU
    rd = _real_range(jnp.asarray(min_d, jnp.float32).reshape(()),
                     jnp.asarray(max_d, jnp.float32).reshape(()))
    rw = _real_range(jnp.asarray(min_w, jnp.float32).reshape(()),
                     jnp.asarray(max_w, jnp.float32).reshape(()))
    omin, omax = _int32_range(rd, rw)
    if bias is not None:
        bias32 = _rescale_bias_to_acc(bias, min_b, max_b, omax)
        out = out + bias32.reshape((1, -1) + (1,) * (out.ndim - 2))
    return out, jnp.float32(1) * omin, jnp.float32(1) * omax


@register("_contrib_quantized_fully_connected", num_inputs=None, num_outputs=3,
          fargnames=_q_argnames, finfer_params=_qfc_param_shapes,
          differentiable=False)
def _quantized_fc(*args, num_hidden=0, no_bias=True, flatten=True):
    """int8 x·Wᵀ with int32 accumulation (ref: quantized_fully_connected.cc)."""
    if no_bias:
        data, weight, min_d, max_d, min_w, max_w = args
        bias = None
    else:
        data, weight, bias, min_d, max_d, min_w, max_w, min_b, max_b = args
    x = data.reshape((data.shape[0], -1)) if flatten else data
    out = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    rd = _real_range(jnp.asarray(min_d, jnp.float32).reshape(()),
                     jnp.asarray(max_d, jnp.float32).reshape(()))
    rw = _real_range(jnp.asarray(min_w, jnp.float32).reshape(()),
                     jnp.asarray(max_w, jnp.float32).reshape(()))
    omin, omax = _int32_range(rd, rw)
    if bias is not None:
        out = out + _rescale_bias_to_acc(bias, min_b, max_b, omax)
    return out, jnp.float32(1) * omin, jnp.float32(1) * omax


@register("_contrib_quantized_pooling", num_inputs=3, num_outputs=3,
          input_names=("data", "min_data", "max_data"),
          differentiable=False)
def _quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                       global_pool=False, stride=(), pad=(),
                       pooling_convention="valid", cudnn_off=False, p_value=2,
                       count_include_pad=True):
    """Pooling on the int8 grid (ref: quantized_pooling.cc) — max pool is
    exact in int8; avg pool averages in int32 then rounds back."""
    min_d = jnp.asarray(min_data, jnp.float32).reshape(())
    max_d = jnp.asarray(max_data, jnp.float32).reshape(())
    if pool_type == "max":
        out = _pooling(data, kernel=kernel, pool_type="max",
                       global_pool=global_pool, stride=stride, pad=pad,
                       pooling_convention=pooling_convention)
    elif pool_type == "avg":
        s = _pooling(data.astype(jnp.int32), kernel=kernel, pool_type="sum",
                     global_pool=global_pool, stride=stride, pad=pad,
                     pooling_convention=pooling_convention)
        if count_include_pad:
            k = data.shape[2:] if global_pool else tuple(kernel)
            cnt = float(np.prod(k))
        else:
            # per-window element count, matching the float op's borders
            cnt = _pooling(jnp.ones(data.shape, jnp.int32), kernel=kernel,
                           pool_type="sum", global_pool=global_pool,
                           stride=stride, pad=pad,
                           pooling_convention=pooling_convention)
        out = jnp.clip(jnp.rint(s / cnt),
                       -INT8_MAX, INT8_MAX).astype(data.dtype)
    else:
        raise ValueError("quantized_pooling supports max/avg, got %r"
                         % pool_type)
    return out, min_d, max_d


@register("_contrib_quantized_flatten", num_inputs=3, num_outputs=3,
          input_names=("data", "min_data", "max_data"),
          differentiable=False)
def _quantized_flatten(data, min_data, max_data):
    """ref: quantized_flatten.cc — reshape, range passes through."""
    return (data.reshape((data.shape[0], -1)),
            jnp.asarray(min_data, jnp.float32).reshape(()),
            jnp.asarray(max_data, jnp.float32).reshape(()))


@register("_contrib_quantized_act", num_inputs=3, num_outputs=3,
          input_names=("data", "min_data", "max_data"),
          differentiable=False)
def _quantized_act(data, min_data, max_data, act_type="relu"):
    """relu directly on the int8 grid (ref: the role of MKLDNN's fused
    conv+relu subgraphs — round 5 adds it as a first-class op because
    XLA cannot fuse across an int8 dequantize boundary).  Symmetric
    zero-centered codes make relu a plain elementwise max with 0; the
    code->value scale is unchanged, so the range passes through (the
    negative half of the grid simply goes unused)."""
    zero = jnp.zeros((), data.dtype)
    return (jnp.maximum(data, zero),
            jnp.asarray(min_data, jnp.float32).reshape(()) * 1,
            jnp.asarray(max_data, jnp.float32).reshape(()) * 1)


@register("_contrib_quantized_elemwise_add", num_inputs=6, num_outputs=3,
          input_names=("lhs", "rhs", "min_lhs", "max_lhs",
                       "min_rhs", "max_rhs"),
          differentiable=False)
def _quantized_elemwise_add(lhs, rhs, min_lhs, max_lhs, min_rhs, max_rhs):
    """Residual add without leaving the quantized domain.

    The two int8 operands carry different scales, so each code is
    rescaled onto a common int32 accumulator grid whose extremes map to
    ±(r_lhs + r_rhs) — the exact bound of the sum — and the add happens
    there; a requantize (NEED_REQUANTIZE) narrows back to int8.  The
    per-element math is two fused multiply-adds in registers: no f32
    tensor ever touches HBM, which is the entire point (a dequantized
    residual add costs three full f32 activation passes).
    ref: the reference gains this from MKLDNN sum fusion; modeled on
    quantization_utils.h QuantizationRangeForMultiplication style
    range algebra."""
    ra = _real_range(jnp.asarray(min_lhs, jnp.float32).reshape(()),
                     jnp.asarray(max_lhs, jnp.float32).reshape(()))
    rb = _real_range(jnp.asarray(min_rhs, jnp.float32).reshape(()),
                     jnp.asarray(max_rhs, jnp.float32).reshape(()))
    r_out = ra + rb
    acc = float(np.iinfo(np.int32).max)
    ka = ra * (acc / (INT8_MAX * r_out))     # int32 units per lhs code
    kb = rb * (acc / (INT8_MAX * r_out))
    out = jnp.rint(lhs.astype(jnp.float32) * ka
                   + rhs.astype(jnp.float32) * kb).astype(jnp.int32)
    return out, -r_out, r_out * 1


# ---------------------------------------------------------------------------
# Graph-pass metadata: which float ops have a quantized twin, and which
# quantized ops emit int32 that must be requantized (ref: FQuantizedOp /
# FNeedRequantize attrs consumed by quantize_graph_pass.cc).
# ---------------------------------------------------------------------------

QUANTIZED_OP_MAP = {
    "Convolution": "_contrib_quantized_conv",
    "FullyConnected": "_contrib_quantized_fully_connected",
    "Pooling": "_contrib_quantized_pooling",
    "Flatten": "_contrib_quantized_flatten",
    "Activation": "_contrib_quantized_act",
    # elemwise_add aliases to broadcast_add in the registry: map both
    "elemwise_add": "_contrib_quantized_elemwise_add",
    "broadcast_add": "_contrib_quantized_elemwise_add",
}

NEED_REQUANTIZE = {"_contrib_quantized_conv",
                   "_contrib_quantized_fully_connected",
                   "_contrib_quantized_elemwise_add"}

# float-op params that the quantized twin does not accept
_DROP_PARAMS = {"Flatten": ("axis",)}


def quantizable(op_name, params):
    """Whether this node can be replaced by its int8 twin under ``params``
    (Pooling only for max/avg, matching quantized_pooling.cc; Activation
    only for relu — the int8 grid is relu-closed, other activations
    need the float path)."""
    if op_name not in QUANTIZED_OP_MAP:
        return False
    if op_name == "Pooling" and params.get("pool_type", "max") not in ("max", "avg"):
        return False
    if op_name == "Activation" and params.get("act_type") != "relu":
        return False
    return True
