"""Core neural-network operators.

TPU-native equivalents of src/operator/nn/ (Convolution, FullyConnected,
BatchNorm, Pooling, Activation, Dropout, LRN, softmax, LayerNorm, ...) and
the legacy output/loss ops (softmax_output.cc, regression_output.cc).
Where the reference dispatches to cuDNN (src/operator/nn/cudnn/), we lower to
XLA convolutions / reduce_window — the TPU's MXU + fusion pipeline is the
"cuDNN" here, with autotuning owned by XLA (SURVEY §2.2 cuDNN row).

Layout note: the public API keeps MXNet's NCHW/OIHW conventions; XLA:TPU's
layout assignment re-tiles internally, so user code ports unchanged.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _fc_param_shapes(data_shape, params):
    """ref: fully_connected.cc FInferShape fills weight/bias from data."""
    nh = params.get("num_hidden", 0)
    flatten = params.get("flatten", True)
    in_units = int(np.prod(data_shape[1:])) if flatten else data_shape[-1]
    return {"weight": (nh, in_units), "bias": (nh,)}


def _conv_param_shapes(data_shape, params):
    """ref: convolution.cc FInferShape."""
    nf = params.get("num_filter", 0)
    ng = params.get("num_group", 1)
    kernel = tuple(params.get("kernel", ()))
    return {"weight": (nf, data_shape[1] // ng) + kernel, "bias": (nf,)}


def _deconv_param_shapes(data_shape, params):
    """ref: deconvolution-inl.h — weight is (in, out/groups, *k)."""
    nf = params.get("num_filter", 0)
    ng = params.get("num_group", 1)
    kernel = tuple(params.get("kernel", ()))
    return {"weight": (data_shape[1], nf // ng) + kernel, "bias": (nf,)}


def _channel_param_shapes(data_shape, params):
    c = data_shape[params.get("axis", 1) % len(data_shape)]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,),
            "moving_var": (c,)}


def _layernorm_param_shapes(data_shape, params):
    c = data_shape[params.get("axis", -1) % len(data_shape)]
    return {"gamma": (c,), "beta": (c,)}


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


# ---------------------------------------------------------------------------
# FullyConnected (ref: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------

@register("FullyConnected", num_inputs=None,
          input_names=("data", "weight", "bias"),
          finfer_params=_fc_param_shapes)
def _fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False, flatten=True):
    """y = x·Wᵀ + b on the MXU (ref: fully_connected.cc:1)."""
    x = data.reshape((data.shape[0], -1)) if flatten else data
    out = jnp.dot(x, weight.T, preferred_element_type=jnp.promote_types(x.dtype, weight.dtype))
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (ref: src/operator/nn/convolution.cc:383-509)
# ---------------------------------------------------------------------------

@register("Convolution", num_inputs=None,
          input_names=("data", "weight", "bias"),
          finfer_params=_conv_param_shapes, aliases=("Convolution_v1",))
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                 num_filter=0, num_group=1, no_bias=False, workspace=1024,
                 cudnn_tune=None, cudnn_off=False, layout=None):
    """N-d convolution, NCHW/OIHW (ref: convolution.cc; cuDNN path replaced
    by XLA's conv which tiles directly onto the MXU)."""
    nd = len(kernel) if kernel else data.ndim - 2
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    spatial = "".join("DHW"[3 - nd + i] for i in range(nd))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.promote_types(data.dtype, weight.dtype))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", num_inputs=None,
          input_names=("data", "weight", "bias"),
          finfer_params=_deconv_param_shapes)
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                   adj=(), target_shape=(), num_filter=0, num_group=1, no_bias=True,
                   workspace=512, cudnn_tune=None, cudnn_off=False, layout=None):
    """Transposed convolution (ref: src/operator/nn/deconvolution.cc).

    Implemented as the gradient of Convolution: lhs-dilated conv with the
    spatially-flipped kernel — exactly what XLA fuses best.  MXNet deconv
    weight layout is (in_c, out_c/g, kH, kW) i.e. IOHW.
    """
    nd = len(kernel)
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    adj = _pair(adj, nd) if adj else (0,) * nd
    spatial = "".join("DHW"[3 - nd + i] for i in range(nd))
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "IO" + spatial, "NC" + spatial))
    # effective kernel extent k' = dilate*(k-1)+1; output pad per side:
    pads = []
    for i in range(nd):
        k_eff = dilate[i] * (kernel[i] - 1) + 1
        lo = k_eff - 1 - pad[i]
        hi = k_eff - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    out = lax.conv_general_dilated(
        data, jnp.flip(weight, axis=tuple(range(2, 2 + nd))),
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.promote_types(data.dtype, weight.dtype))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (ref: src/operator/nn/pooling.cc, pool.h)
# ---------------------------------------------------------------------------

@register("Pooling", num_inputs=1, aliases=("Pooling_v1",))
def _pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(), pad=(),
             pooling_convention="valid", cudnn_off=False, p_value=2,
             count_include_pad=True):
    """max/avg/sum/lp pooling via lax.reduce_window (ref: pooling.cc)."""
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd) if stride else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    if pooling_convention == "full":
        # ceil-mode: pad high edge so ceil((x+2p-k)/s)+1 windows fit
        pads = []
        for i in range(nd):
            x = data.shape[2 + i]
            out_sz = int(np.ceil((x + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - x - pad[i]
            pads.append((pad[i], max(needed, pad[i])))
    else:
        pads = [(p, p) for p in pad]
    padding = ((0, 0), (0, 0)) + tuple(pads)
    if pool_type == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            # literal -inf: keeps XLA's select-and-scatter autodiff path
            return lax.reduce_window(data, -jnp.inf, lax.max,
                                     window, strides, padding)
        init = jnp.asarray(jnp.iinfo(data.dtype).min, data.dtype)
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        zero = (0.0 if jnp.issubdtype(data.dtype, jnp.floating)
                else jnp.asarray(0, data.dtype))
        s = lax.reduce_window(data, zero, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            return s / float(np.prod(kernel))
        ones = jnp.ones(data.shape, data.dtype)
        cnt = lax.reduce_window(ones, zero, lax.add, window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        p = jnp.abs(data) ** p_value
        zero = (0.0 if jnp.issubdtype(p.dtype, jnp.floating)
                else jnp.asarray(0, p.dtype))
        s = lax.reduce_window(p, zero, lax.add, window, strides, padding)
        return s ** (1.0 / p_value)
    raise ValueError("unknown pool_type %r" % pool_type)


@register("UpSampling", num_inputs=None)
def _upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
                multi_input_mode="concat", workspace=512):
    """ref: src/operator/upsampling.cc (nearest + bilinear via XLA resize)."""
    data = args[0]
    n, c, h, w = data.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


# ---------------------------------------------------------------------------
# Normalization (ref: src/operator/nn/batch_norm.cc, layer_norm.cc, lrn.cc)
# ---------------------------------------------------------------------------

def _bn_reduce_layout(data, axis):
    axis = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    m = float(np.prod([data.shape[i] for i in red]))
    return axis, red, bshape, m


def _bn_train_stats(data, axis):
    """Batch mean/var in f32 over a (possibly bf16) activation.

    Two passes, both reading the input at its native precision with an f32
    accumulator (XLA converts in-register — no f32 copy of the activation
    ever hits HBM).  Pass 2 fuses convert+sub+square into the reduction.
    The shifted two-pass form stays cancellation-safe where the fused
    E[x²]−E[x]² single pass silently loses channels with |mean| ≫ std.
    """
    _, red, bshape, _ = _bn_reduce_layout(data, axis)
    mean = jnp.mean(data, axis=red, dtype=jnp.float32)
    var = jnp.mean(
        jnp.square(data.astype(jnp.float32) - mean.reshape(bshape)), axis=red)
    return mean, var


def _bn_train_core_fwd(data, gamma, beta, axis, eps, fix_gamma):
    axis, _, bshape, _ = _bn_reduce_layout(data, axis)
    mean, var = _bn_train_stats(data, axis)
    inv = lax.rsqrt(var + eps)
    g = jnp.ones_like(inv) if fix_gamma else gamma.astype(jnp.float32)
    scale = g * inv
    out = ((data.astype(jnp.float32) - mean.reshape(bshape))
           * scale.reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape)).astype(data.dtype)
    return out, mean, var, inv, scale


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train_core(data, gamma, beta, axis, eps, fix_gamma):
    """Training-mode BN with a hand-derived backward.

    Autodiff of the two-pass statistics chain costs ~2 extra full passes
    over the activation in f32; the closed-form BN backward (the same
    d-gamma/d-beta/dx decomposition cuDNN and batch_norm.cc:89 use) needs
    exactly two fused reductions over (dy, x) plus one elementwise pass —
    on the ResNet-50 bench this was worth ~20% end-to-end.
    """
    out, mean, var, _, _ = _bn_train_core_fwd(data, gamma, beta, axis, eps,
                                              fix_gamma)
    return out, mean, var


def _bn_train_core_fwd_rule(data, gamma, beta, axis, eps, fix_gamma):
    # symbolic_zeros=True wraps primal inputs in CustomVJPPrimal
    data, gamma, beta = data.value, gamma.value, beta.value
    out, mean, var, inv, scale = _bn_train_core_fwd(data, gamma, beta, axis,
                                                    eps, fix_gamma)
    return (out, mean, var), (data, gamma, mean, inv, scale)


def _bn_train_core_bwd_rule(axis, eps, fix_gamma, res, cotangents):
    from jax.custom_derivatives import SymbolicZero
    dy, ct_mean, ct_var = cotangents
    data, gamma, mean, inv, scale = res
    axis, red, bshape, m = _bn_reduce_layout(data, axis)
    xc = data.astype(jnp.float32) - mean.reshape(bshape)
    if isinstance(dy, SymbolicZero):
        dx = jnp.zeros(data.shape, jnp.float32)
        dgamma_raw = jnp.zeros_like(mean)
        dbeta = jnp.zeros_like(mean)
    else:
        dyf = dy.astype(jnp.float32)
        xhat = xc * inv.reshape(bshape)
        # both reductions read (dy, x) once — XLA multi-output fuses them
        dbeta = jnp.sum(dyf, axis=red)
        dgamma_raw = jnp.sum(dyf * xhat, axis=red)
        dx = scale.reshape(bshape) * \
            (dyf - (dbeta.reshape(bshape) +
                    xhat * dgamma_raw.reshape(bshape)) / m)
    # Cotangents on the batch-statistics outputs (graphs that differentiate
    # through output_mean_var) fold straight into dx: dmean/dx = 1/m and
    # dvar/dx = 2(x-mean)/m (the cross-term through the mean cancels).  In
    # ordinary training graphs they are SymbolicZero and cost nothing.
    if not isinstance(ct_mean, SymbolicZero):
        dx = dx + ct_mean.astype(jnp.float32).reshape(bshape) / m
    if not isinstance(ct_var, SymbolicZero):
        dx = dx + ct_var.astype(jnp.float32).reshape(bshape) * 2.0 * xc / m
    dx = dx.astype(data.dtype)
    dgamma = (jnp.zeros_like(gamma) if fix_gamma
              else dgamma_raw.astype(gamma.dtype))
    return dx, dgamma, dbeta.astype(gamma.dtype)


_bn_train_core.defvjp(_bn_train_core_fwd_rule, _bn_train_core_bwd_rule,
                      symbolic_zeros=True)


@register("BatchNorm", num_inputs=5, num_outputs=3, num_visible_outputs=1,
          takes_is_train=True, nograd_inputs=(3, 4), aliases=("BatchNorm_v1",),
          input_names=("data", "gamma", "beta", "moving_mean", "moving_var"),
          aux_input_names=("moving_mean", "moving_var"),
          finfer_params=_channel_param_shapes,
          fvisible=lambda params, n: n if params.get("output_mean_var") else 1)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, is_train=False):
    """ref: batch_norm.cc:89.  Outputs (out, batch_mean, batch_var); the
    front-end updates the moving_* aux states with `momentum` outside the op,
    mirroring how the reference mutates aux arrays in-place."""
    if is_train and not use_global_stats:
        return _bn_train_core(data, gamma, beta, axis, eps, bool(fix_gamma))
    # inference / global-stats path: pure elementwise, autodiff is optimal
    axis, _, bshape, _ = _bn_reduce_layout(data, axis)
    mean = moving_mean.astype(jnp.float32)
    var = moving_var.astype(jnp.float32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = lax.rsqrt(var + eps)
    # normalize in f32 then cast once: x·s + (β − μ·s) folded in bf16
    # loses the large-mean channels to cancellation (bf16 mantissa ~8
    # bits), while (x − μ) first keeps only the final rounding; XLA
    # converts in-register so the HBM traffic stays at input precision
    out = (data.astype(jnp.float32) - mean.reshape(bshape)) * \
        (g.astype(jnp.float32) * inv).reshape(bshape) + \
        beta.astype(jnp.float32).reshape(bshape)
    return out.astype(data.dtype), mean, var


@register("LayerNorm", num_inputs=3, input_names=("data", "gamma", "beta"),
          finfer_params=_layernorm_param_shapes)
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """ref: src/operator/nn/layer_norm.cc"""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm", num_inputs=3,
          input_names=("data", "gamma", "beta"),
          finfer_params=lambda ds, p: {"gamma": (ds[1],), "beta": (ds[1],)})
def _instance_norm(data, gamma, beta, eps=1e-3):
    """ref: src/operator/instance_norm.cc"""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) + beta.reshape(bshape)


@register("LRN", num_inputs=1)
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (ref: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    summed = lax.reduce_window(sq, 0.0, lax.add, (1, nsize, 1, 1), (1, 1, 1, 1),
                               ((0, 0), (half, half), (0, 0), (0, 0)))
    return data * jnp.power(knorm + (alpha / nsize) * summed, -beta)


# ---------------------------------------------------------------------------
# Activations (ref: src/operator/nn/activation.cc, leaky_relu.cc, softmax.cc)
# ---------------------------------------------------------------------------

@register("Activation", num_inputs=1)
def _activation(data, act_type="relu"):
    """ref: activation.cc"""
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %r" % act_type)


@register("LeakyReLU", num_inputs=None, needs_rng=True, takes_is_train=True,
          fargnames=lambda p: ("data", "gamma") if p.get("act_type") == "prelu"
          else ("data",))
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334, rng=None, is_train=False):
    """ref: src/operator/leaky_relu.cc (leaky/elu/prelu/rrelu)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "rrelu":
        if is_train:
            s = jax.random.uniform(rng, data.shape, data.dtype, lower_bound, upper_bound)
        else:
            s = jnp.asarray((lower_bound + upper_bound) / 2.0, data.dtype)
        return jnp.where(data > 0, data, s * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("softmax", num_inputs=1)
def _softmax(data, axis=-1, temperature=None):
    """ref: src/operator/nn/softmax.cc"""
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax", num_inputs=1)
def _log_softmax(data, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("SoftmaxActivation", num_inputs=1)
def _softmax_activation(data, mode="instance"):
    """ref: src/operator/nn/softmax_activation.cc"""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("Dropout", num_inputs=1, needs_rng=True, takes_is_train=True)
def _dropout(data, p=0.5, mode="training", axes=(), rng=None, is_train=False):
    """Inverted dropout (ref: src/operator/nn/dropout.cc)."""
    if (not is_train and mode != "always") or p == 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, shape)
    return jnp.where(mask, data / keep, jnp.zeros((), data.dtype))


# ---------------------------------------------------------------------------
# Legacy output/loss ops with integrated gradients
# (ref: src/operator/softmax_output.cc, regression_output.cc, svm_output.cc)
# ---------------------------------------------------------------------------

def _custom_loss_fwd_bwd(fwd_fn, grad_fn):
    """Build an op whose backward ignores upstream grad, like the reference's
    *Output ops: backward of SoftmaxOutput is (softmax - onehot(label)) no
    matter what (softmax_output.cc)."""
    @jax.custom_vjp
    def f(data, label):
        return fwd_fn(data, label)

    def fwd(data, label):
        return fwd_fn(data, label), (data, label)

    def bwd(res, g):
        data, label = res
        return grad_fn(data, label), jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f


@register("SoftmaxOutput", num_inputs=2, nograd_inputs=(1,),
          input_names=("data", "label"), aliases=("Softmax",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0, multi_output=False,
                    use_ignore=False, preserve_shape=False, normalization="null",
                    out_grad=False, smooth_alpha=0.0):
    """ref: src/operator/softmax_output.cc — fwd softmax, bwd p - onehot(y)."""
    axis = 1 if (multi_output or preserve_shape or data.ndim > 2) else -1

    def fwd_fn(d, l):
        return jax.nn.softmax(d, axis=axis)

    def grad_fn(d, l):
        p = jax.nn.softmax(d, axis=axis)
        k = d.shape[axis]
        lab = l.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, k, dtype=d.dtype, axis=axis)
        if smooth_alpha:
            oh = oh * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - oh)
        g = p - oh
        if use_ignore:
            valid = (l != ignore_label).astype(d.dtype)
            g = g * jnp.expand_dims(valid, axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / d.shape[0]
        elif normalization == "valid" and use_ignore:
            nvalid = jnp.maximum(jnp.sum(l != ignore_label), 1).astype(d.dtype)
            return g * (grad_scale / nvalid)
        return g * scale

    return _custom_loss_fwd_bwd(fwd_fn, grad_fn)(data, label)


@register("LinearRegressionOutput", num_inputs=2, nograd_inputs=(1,),
          input_names=("data", "label"))
def _linear_regression_output(data, label, grad_scale=1.0):
    """ref: regression_output.cc — fwd identity, bwd (pred - label)."""
    return _custom_loss_fwd_bwd(
        lambda d, l: d,
        lambda d, l: (d - l.reshape(d.shape)) * grad_scale)(data, label)


@register("MAERegressionOutput", num_inputs=2, nograd_inputs=(1,),
          input_names=("data", "label"))
def _mae_regression_output(data, label, grad_scale=1.0):
    return _custom_loss_fwd_bwd(
        lambda d, l: d,
        lambda d, l: jnp.sign(d - l.reshape(d.shape)) * grad_scale)(data, label)


@register("LogisticRegressionOutput", num_inputs=2, nograd_inputs=(1,),
          input_names=("data", "label"))
def _logistic_regression_output(data, label, grad_scale=1.0):
    return _custom_loss_fwd_bwd(
        lambda d, l: jax.nn.sigmoid(d),
        lambda d, l: (jax.nn.sigmoid(d) - l.reshape(d.shape)) * grad_scale)(data, label)


@register("SVMOutput", num_inputs=2, nograd_inputs=(1,),
          input_names=("data", "label"))
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """ref: src/operator/svm_output.cc"""
    def grad_fn(d, l):
        k = d.shape[1]
        oh = jax.nn.one_hot(l.astype(jnp.int32), k, dtype=d.dtype)
        if use_linear:
            viol = ((margin - d) * oh + (margin + d) * (1 - oh)) > 0
            g = jnp.where(viol, (1 - oh) - oh, 0.0) * regularization_coefficient
        else:
            score_y = jnp.sum(d * oh, axis=1, keepdims=True)
            viol = (d - score_y + margin) > 0
            g_other = jnp.where(viol & (oh == 0), 2.0 * (d - score_y + margin), 0.0)
            g = g_other - oh * jnp.sum(g_other, axis=1, keepdims=True)
            g = g * regularization_coefficient
        return g.astype(d.dtype)

    return _custom_loss_fwd_bwd(lambda d, l: d, grad_fn)(data, label)
