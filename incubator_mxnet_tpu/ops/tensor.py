"""Shape-manipulation, indexing, and ordering operators.

TPU-native equivalents of src/operator/tensor/matrix_op.cc, indexing_op.cc,
ordering_op.cc, init_op.cc, control_flow_op.cc (reference, SURVEY §2.2).
All shape arithmetic happens in Python at trace time (shapes are static under
XLA), so these lower to pure lax reshapes/slices/gathers.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def infer_reshape(src_shape, target, reverse=False):
    """MXNet Reshape special codes (ref: matrix_op-inl.h ReshapeParam docs):

    0 = copy this dim; -1 = infer; -2 = copy all remaining dims;
    -3 = merge next two dims; -4 = split next dim by the following two values.
    """
    src = list(src_shape)
    tgt = list(target)
    if reverse:
        src = src[::-1]
        tgt = [t for t in tgt[::-1]]
        # -4's two split factors travel with it; reversing swaps them
        out = infer_reshape(src, tgt, reverse=False)
        return tuple(out[::-1])
    out = []
    i = 0  # index into src
    j = 0
    while j < len(tgt):
        t = tgt[j]
        if t == 0:
            out.append(src[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1  # placeholder; src cursor advance is heuristic
        elif t == -2:
            out.extend(src[i:]); i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif t == -4:
            a, b = tgt[j + 1], tgt[j + 2]
            d = src[i]
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(t)
            # advance src cursor heuristically (only matters for 0/-1 codes)
            if i < len(src):
                i += 1
        j += 1
    known = 1
    for d in out:
        if d != -1:
            known *= d
    total = int(np.prod(src_shape)) if src_shape else 1
    return tuple(d if d != -1 else total // max(known, 1) for d in out)


@register("Reshape", num_inputs=1, aliases=("reshape",))
def _reshape(data, shape=(), reverse=False):
    """ref: src/operator/tensor/matrix_op.cc Reshape"""
    return jnp.reshape(data, infer_reshape(data.shape, shape, reverse))


@register("Flatten", num_inputs=1, aliases=("flatten",))
def _flatten(data):
    """ref: matrix_op.cc Flatten — collapse all but first axis."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose", num_inputs=1)
def _transpose(data, axes=()):
    """ref: matrix_op.cc transpose"""
    return jnp.transpose(data, axes if axes else None)


@register("expand_dims", num_inputs=1)
def _expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze", num_inputs=1)
def _squeeze(data, axis=None):
    return jnp.squeeze(data, axis)


@register("swapaxes", num_inputs=1, aliases=("SwapAxis",))
def _swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("slice", num_inputs=1, aliases=("crop",))
def _slice(data, begin=(), end=(), step=()):
    """ref: matrix_op.cc slice (begin/end may contain None)."""
    step = step or (None,) * len(begin)
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("_bulk_view_extract", num_inputs=1)
def _bulk_view_extract(data, offset=0, shape=()):
    """Contiguous row-major view extraction (engine deferred views): the
    program-node form of NDArray._read over a (base, offset, shape) view,
    recorded inside a bulk segment so view creation no longer flushes."""
    flat = jnp.reshape(data, (-1,))
    size = 1
    for s in shape:
        size *= s
    return jnp.reshape(lax.slice(flat, (offset,), (offset + size,)), shape)


@register("_bulk_view_write", num_inputs=2)
def _bulk_view_write(base, value, offset=0):
    """Write-through to a deferred view: rebind the base's buffer with the
    view's span replaced (the program-node form of NDArray._write's
    scatter into the base)."""
    flat = jnp.reshape(base, (-1,))
    flat = lax.dynamic_update_slice(
        flat, jnp.reshape(value, (-1,)).astype(base.dtype), (offset,))
    return jnp.reshape(flat, base.shape)


@register("slice_axis", num_inputs=1)
def _slice_axis(data, axis=0, begin=0, end=None):
    """ref: matrix_op.cc slice_axis"""
    axis = axis % data.ndim
    n = data.shape[axis]
    b = begin if begin >= 0 else begin + n
    e = n if end is None else (end if end >= 0 else end + n)
    return lax.slice_in_dim(data, b, e, axis=axis)


@register("slice_like", num_inputs=2, nograd_inputs=(1,))
def _slice_like(data, shape_like, axes=()):
    """ref: matrix_op.cc slice_like"""
    axes = axes or tuple(range(shape_like.ndim))
    out = data
    for a in axes:
        out = lax.slice_in_dim(out, 0, shape_like.shape[a], axis=a)
    return out


@register("Concat", num_inputs=None, aliases=("concat",))
def _concat(*args, dim=1, num_args=None):
    """ref: src/operator/nn/concat.cc"""
    return jnp.concatenate(args, axis=dim)


@register("stack", num_inputs=None)
def _stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register("SliceChannel", num_inputs=1, num_outputs=1, aliases=("split",),
          fnum_outputs=lambda p: int(p.get("num_outputs", 1)))
def _split(data, num_outputs=1, axis=1, squeeze_axis=False):
    """ref: src/operator/slice_channel.cc — returns a list of outputs.

    num_outputs is dynamic metadata; the front-end special-cases the output
    count (see ndarray/register.py analogue).
    """
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("repeat", num_inputs=1)
def _repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("tile", num_inputs=1)
def _tile(data, reps=()):
    return jnp.tile(data, reps)


@register("reverse", num_inputs=1, aliases=("flip",))
def _reverse(data, axis=()):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, ax)


@register("Pad", num_inputs=1, aliases=("pad",))
def _pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """ref: src/operator/pad.cc (pad_width in mxnet flat before/after pairs)."""
    pw = [(int(pad_width[2 * i]), int(pad_width[2 * i + 1]))
          for i in range(len(pad_width) // 2)]
    mode_map = {"constant": "constant", "edge": "edge", "reflect": "reflect"}
    if mode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=mode_map[mode])


@register("space_to_depth", num_inputs=1)
def _space_to_depth(data, block_size=1):
    """ref: matrix_op.cc space_to_depth (NCHW)."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("depth_to_space", num_inputs=1)
def _depth_to_space(data, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)

# ---------------------------------------------------------------------------
# indexing (reference: src/operator/tensor/indexing_op.cc)
# ---------------------------------------------------------------------------


@register("take", num_inputs=2, nograd_inputs=(1,))
def _take(a, indices, axis=0, mode="clip"):
    """ref: indexing_op.cc Take"""
    idx = indices.astype(jnp.int32)
    n = a.shape[axis]
    if mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, n)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", num_inputs=2, nograd_inputs=(1,), aliases=("pick",))
def _pick(data, index, axis=1, keepdims=False):
    """ref: indexing_op.cc pick/batch_take"""
    idx = index.astype(jnp.int32)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("Embedding", num_inputs=2, nograd_inputs=(0,),
          input_names=("data", "weight"),
          finfer_params=lambda ds, p: {"weight": (p.get("input_dim", 0),
                                                  p.get("output_dim", 0))})
def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32", sparse_grad=False):
    """ref: indexing_op.cc Embedding — gather rows of weight.

    On TPU this is a gather from HBM; the rowsparse-gradient variant of the
    reference maps to the sparse module's row-sparse grad path.
    """
    idx = data.astype(jnp.int32)
    # clip, not fill: jnp.take's NaN-fill default turns one rounded-up
    # index (e.g. a bf16-cast token id at the vocab edge) into a NaN row
    # that poisons the whole step; the reference clamps too
    return jnp.take(weight, idx, axis=0, mode="clip")


@register("one_hot", num_inputs=1, differentiable=False)
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    """ref: indexing_op.cc one_hot"""
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * on_value + (1.0 - oh) * off_value


@register("gather_nd", num_inputs=2, nograd_inputs=(1,))
def _gather_nd(data, indices):
    """ref: indexing_op.cc gather_nd — indices shape (M, ...)."""
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", num_inputs=2, nograd_inputs=(1,))
def _scatter_nd(data, indices, shape=()):
    """ref: indexing_op.cc scatter_nd"""
    out = jnp.zeros(shape, dtype=data.dtype)
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return out.at[idx].add(data)


@register("where", num_inputs=3)
def _where(condition, x, y):
    """ref: src/operator/tensor/control_flow_op.cc where"""
    return jnp.where(condition != 0, x, y)

# ---------------------------------------------------------------------------
# ordering (reference: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------


@register("topk", num_inputs=1, differentiable=False,
          fnum_outputs=lambda p: 2 if p.get("ret_typ") == "both" else 1)
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """ref: ordering_op.cc topk"""
    x = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        vals, idxs = lax.top_k(-x, k)
        vals = -vals
    else:
        vals, idxs = lax.top_k(x, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxs.astype(jnp.dtype(dtype))
    if ret_typ == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1), data.shape[axis], dtype=data.dtype)
        return jnp.moveaxis(oh.sum(-2), -1, axis)
    # 'both'
    return vals, idxs.astype(jnp.dtype(dtype))


@register("sort", num_inputs=1, differentiable=False)
def _sort(data, axis=-1, is_ascend=True):
    s = jnp.sort(data, axis=axis)
    return s if is_ascend else jnp.flip(s, axis=axis)


@register("argsort", num_inputs=1, differentiable=False)
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    s = jnp.argsort(data, axis=axis)
    if not is_ascend:
        s = jnp.flip(s, axis=axis)
    return s.astype(jnp.dtype(dtype))


@register("shuffle", num_inputs=1, differentiable=False, needs_rng=True, aliases=("_shuffle",))
def _shuffle(data, rng=None):
    """ref: src/operator/random/shuffle_op.cc — permute along first axis."""
    perm = jax.random.permutation(rng, data.shape[0])
    return jnp.take(data, perm, axis=0)

# ---------------------------------------------------------------------------
# casts & identity
# ---------------------------------------------------------------------------


@register("Cast", num_inputs=1, aliases=("cast",))
def _cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register("_copy", num_inputs=1, aliases=("identity",))
def _copy(data):
    return jnp.asarray(data)


@register("BlockGrad", num_inputs=1, differentiable=False, aliases=("stop_gradient",))
def _blockgrad(data):
    """ref: elemwise_unary_op_basic.cc BlockGrad"""
    return lax.stop_gradient(data)


@register("make_loss", num_inputs=1, aliases=("MakeLoss",))
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """ref: src/operator/make_loss.cc — identity fwd, grad_scale bwd."""
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (jnp.full_like(g, grad_scale),)

    f.defvjp(fwd, bwd)
    return f(data)


@register("_grad_add", num_inputs=2)
def _grad_add(lhs, rhs):
    return lhs + rhs

# ---------------------------------------------------------------------------
# sequence ops (reference: src/operator/sequence_{last,mask,reverse}.cc)
# ---------------------------------------------------------------------------


def _seq_len_or_full(data, sequence_length, use_sequence_length, time_axis=0):
    if use_sequence_length and sequence_length is not None:
        return sequence_length.astype(jnp.int32)
    return jnp.full((data.shape[1 - time_axis if time_axis == 0 else 0],),
                    data.shape[time_axis], dtype=jnp.int32)


@register("SequenceLast", num_inputs=None)
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    """ref: sequence_last.cc — (T,N,...) pick last valid step per sequence."""
    x = jnp.moveaxis(data, axis, 0)
    T, N = x.shape[0], x.shape[1]
    if use_sequence_length and sequence_length is not None:
        idx = jnp.clip(sequence_length.astype(jnp.int32) - 1, 0, T - 1)
    else:
        idx = jnp.full((N,), T - 1, dtype=jnp.int32)
    return x[idx, jnp.arange(N)]


@register("SequenceMask", num_inputs=None)
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0):
    """ref: sequence_mask.cc — zero (or `value`) out steps beyond seq_len."""
    if not use_sequence_length or sequence_length is None:
        return data
    x = jnp.moveaxis(data, axis, 0)
    T, N = x.shape[0], x.shape[1]
    mask = jnp.arange(T)[:, None] < sequence_length.astype(jnp.int32)[None, :]
    mask = mask.reshape((T, N) + (1,) * (x.ndim - 2))
    out = jnp.where(mask, x, jnp.asarray(value, x.dtype))
    return jnp.moveaxis(out, 0, axis)


@register("SequenceReverse", num_inputs=None)
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    """ref: sequence_reverse.cc — reverse each sequence up to its length."""
    x = jnp.moveaxis(data, axis, 0)
    T = x.shape[0]
    if not use_sequence_length or sequence_length is None:
        out = jnp.flip(x, axis=0)
    else:
        L = sequence_length.astype(jnp.int32)  # (N,)
        t = jnp.arange(T)[:, None]
        src = jnp.where(t < L[None, :], L[None, :] - 1 - t, t)  # (T,N)
        out = jnp.take_along_axis(x, src.reshape((T, x.shape[1]) + (1,) * (x.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


@register("hard_sigmoid", num_inputs=1)
def _hard_sigmoid(data, alpha=0.2, beta=0.5):
    """ref: src/operator/mshadow_op.h hard_sigmoid — clip(a·x + b, 0, 1)."""
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("square_sum", num_inputs=1)
def _square_sum(data, axis=None, keepdims=False, exclude=False):
    """Fused sum(x²) (ref: src/operator/tensor/square_sum.cc — the
    row-sparse fast path lives on the NDArray surface; this is the dense
    registered op so Symbol graphs can reach it)."""
    ax = None if axis is None else (axis if isinstance(axis, (tuple, list))
                                    else (axis,))
    if ax is not None and exclude:
        ax = tuple(i for i in range(data.ndim) if i not in
                   tuple(a % data.ndim for a in ax))
    return jnp.sum(data * data, axis=ax, keepdims=keepdims)


@register("_cast_storage_dense", num_inputs=1, aliases=("cast_storage",))
def _cast_storage_op(data, stype="default"):
    """Registered twin of sparse.cast_storage (ref:
    src/operator/tensor/cast_storage.cc).  Inside a compiled graph every
    tensor is dense; 'row_sparse'/'csr' requests are honored at the
    NDArray surface (ndarray/sparse.py cast_storage), so here the values
    pass through unchanged — the graph stays correct, the storage
    optimization applies in eager mode."""
    return data


@register("_sparse_retain_dense", num_inputs=2, nograd_inputs=(1,),
          aliases=("sparse_retain",))
def _sparse_retain_op(data, indices):
    """Zero all rows except ``indices`` (ref:
    src/operator/tensor/sparse_retain.cc).  Dense semantics of the same
    contract; the rsp fast path is ndarray/sparse.py retain."""
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, jnp.zeros_like(data))
