"""Fused optimizer-update operators.

In the reference, parameter updates are *operators* (src/operator/
optimizer_op.cc: sgd_update, sgd_mom_update, adam_update, ...) so they run on
device inside the engine and on PS servers.  Here each is a pure function
returning the updated weight (+ updated state tensors); the optimizer layer
writes results back into the parameter NDArrays.  Under jit (hybridized
trainer / Module update) the whole update fuses into a handful of XLA
elementwise kernels — the same reason the reference fused them by hand.
Multi-precision (mp_*) variants keep a float32 master copy of bf16/fp16
weights (ref: optimizer_op.cc MP_SGD).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, wd, weight, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", num_inputs=2, differentiable=False, mutate_inputs=(0,))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """ref: optimizer_op.cc sgd_update"""
    g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    return weight - lr * g


@register("_sparse_sgd_update", num_inputs=3, differentiable=False,
          mutate_inputs=(0,))
def _sparse_sgd_update(weight, grad_data, grad_indices, lr=0.01, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """Row-sparse lazy SGD: touch only the rows the gradient occupies
    (ref: optimizer_op.cc SGDUpdateRspRspImpl).  Registered as an op —
    not inline jnp in the optimizer — so ``engine.bulk`` can defer it
    into a training segment like the reference's bulked updates."""
    idx = grad_indices.astype(jnp.int32)
    g = grad_data * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = weight[idx]
    g = g + wd * rows
    return weight.at[idx].set(rows - lr * g)


@register("_sparse_sgd_mom_update", num_inputs=4, differentiable=False,
          num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 3))
def _sparse_sgd_mom_update(weight, grad_data, grad_indices, mom, lr=0.01,
                           momentum=0.0, wd=0.0, rescale_grad=1.0,
                           clip_gradient=-1.0):
    """Row-sparse lazy SGD with momentum (ref: optimizer_op.cc
    SGDMomUpdateRspRspImpl) — momentum state also updated only on the
    occupied rows."""
    idx = grad_indices.astype(jnp.int32)
    g = grad_data * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = weight[idx]
    g = g + wd * rows
    new_rows_m = momentum * mom[idx] - lr * g
    return (weight.at[idx].set(rows + new_rows_m),
            mom.at[idx].set(new_rows_m))


@register("sgd_mom_update", num_inputs=3, differentiable=False, num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 2))
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """ref: optimizer_op.cc sgd_mom_update: mom = m*mom - lr*g; w += mom"""
    g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", num_inputs=3, differentiable=False, num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 2))
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (ref: optimizer.py NAG python updater)."""
    g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("mp_sgd_update", num_inputs=3, differentiable=False, num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 2))
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    """ref: optimizer_op.cc mp_sgd_update — update in f32, cast to w.dtype."""
    g32 = _prep_grad(grad.astype(jnp.float32), wd, weight32, rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * g32
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_inputs=4, differentiable=False, num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(0, 2, 3))
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g32 = _prep_grad(grad.astype(jnp.float32), wd, weight32, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g32
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", num_inputs=4, differentiable=False, num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(0, 2, 3))
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    """ref: optimizer_op.cc adam_update (bias correction folded into lr by the
    Optimizer class, as in python/mxnet/optimizer.py Adam.update)."""
    g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("rmsprop_update", num_inputs=3, differentiable=False, num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 2))
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    """ref: optimizer_op.cc rmsprop_update"""
    g = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", num_inputs=5, differentiable=False,
          num_outputs=4, num_visible_outputs=1,
          mutate_inputs=(0, 2, 3, 4))
def _rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95, gamma2=0.9,
                        epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                        clip_weights=-1.0):
    """ref: optimizer_op.cc rmspropalex_update (Graves' variant)."""
    gr = _prep_grad(grad, wd, weight, rescale_grad, clip_gradient)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(gr)
    new_g = gamma1 * g + (1 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", num_inputs=4, differentiable=False, num_outputs=3, num_visible_outputs=1,
          mutate_inputs=(0, 2, 3))
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op.cc ftrl_update"""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("ftml_update", num_inputs=5, differentiable=False, num_outputs=4, num_visible_outputs=1,
          mutate_inputs=(0, 2, 3, 4))
def _ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """ref: src/operator/optimizer_op.cc ftml_update (FTML, Zheng 2017)."""
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w, d_t, new_v, new_z


@register("signsgd_update", num_inputs=2, differentiable=False, mutate_inputs=(0,))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op.cc signsgd_update (Bernstein et al.)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_inputs=3, differentiable=False, num_outputs=2, num_visible_outputs=1,
          mutate_inputs=(0, 2))
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """ref: optimizer_op.cc signum_update"""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom
