"""Operator library: importing this package registers all operators.

Single registry (registry.py) serving eager + symbolic modes — the TPU-native
analogue of the reference's NNVM registry populated by src/operator/*.cc
static initializers (SURVEY §2.2).
"""
from . import registry
from .registry import Operator, get_op, list_ops, register, alias

# registration side effects
from . import math        # noqa: F401  elementwise/broadcast/reduce/dot
from . import tensor      # noqa: F401  shape/indexing/ordering/sequence
from . import nn          # noqa: F401  conv/fc/norm/act/pool/loss-outputs
from . import init_ops    # noqa: F401  zeros/ones/arange/...
from . import random_ops  # noqa: F401  samplers
from . import optimizer_ops  # noqa: F401  fused updates
from . import rnn         # noqa: F401  fused RNN + CTC
from . import vision      # noqa: F401  detection/sampling (SSD/RCNN/STN)
from . import attention   # noqa: F401  flash attention
from . import linalg      # noqa: F401  LAPACK la_op family + FFT/count_sketch
from . import quantization  # noqa: F401  INT8 quantize/dequantize/quantized_*

__all__ = ["Operator", "get_op", "list_ops", "register", "alias"]
