"""Random sampling operators (ref: src/operator/random/sample_op.cc,
multisample_op.cc, shuffle_op.cc).

The reference maintains per-thread Philox streams (src/common/
random_generator.h); here every op draws from an explicit JAX PRNG key
supplied by the dispatch layer (eager: global counter key from
random_state.py; symbolic: a key threaded through the executor), which is the
TPU-idiomatic equivalent — deterministic, reproducible, trace-safe.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _dt(dtype):
    return jnp.dtype(dtype if dtype not in (None, "None") else "float32")


@register("_random_uniform", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("uniform", "random_uniform"))
def _uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.uniform(rng, shape, _dt(dtype), low, high)


@register("_random_normal", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("normal", "random_normal"))
def _normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return loc + scale * jax.random.normal(rng, shape, _dt(dtype))


@register("_random_gamma", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("random_gamma",))
def _gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.gamma(rng, alpha, shape, _dt(dtype)) * beta


@register("_random_exponential", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("random_exponential",))
def _exponential(lam=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.exponential(rng, shape, _dt(dtype)) / lam


@register("_random_poisson", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("random_poisson",))
def _poisson(lam=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.poisson(rng, lam, shape).astype(_dt(dtype))


@register("_random_negative_binomial", num_inputs=0, differentiable=False, needs_rng=True,
          aliases=("random_negative_binomial",))
def _negative_binomial(k=1, p=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(_dt(dtype))


@register("_random_generalized_negative_binomial", num_inputs=0, differentiable=False,
          needs_rng=True, aliases=("random_generalized_negative_binomial",))
def _gen_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    k1, k2 = jax.random.split(rng)
    g = jax.random.gamma(k1, 1.0 / alpha, shape) * (alpha * mu)
    return jax.random.poisson(k2, g, shape).astype(_dt(dtype))


@register("_sample_multinomial", num_inputs=1, differentiable=False, needs_rng=True,
          fnum_outputs=lambda p: 2 if p.get("get_prob") else 1,
          aliases=("sample_multinomial",))
def _multinomial(data, shape=(), get_prob=False, dtype="int32", rng=None):
    """ref: src/operator/random/multisample_op.cc — sample class ids from
    probability rows."""
    n = 1
    for s in (shape if isinstance(shape, tuple) else (shape,)):
        n *= int(s) if s else 1
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(rng, logits, shape=(n,))
        out = out.reshape(shape) if shape else out.reshape(())
    else:
        out = jax.random.categorical(rng, logits[:, None, :],
                                     shape=(data.shape[0], n), axis=-1)
        out = out.reshape((data.shape[0],) + (tuple(shape) if shape else ()))
    out = out.astype(jnp.dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(jnp.log(jnp.maximum(data, 1e-30)),
                                 out.reshape(data.shape[0], -1).astype(jnp.int32)
                                 if data.ndim > 1 else out.reshape(-1).astype(jnp.int32)[None],
                                 axis=-1)
        return out, lp.reshape(out.shape).astype(jnp.float32)
    return out


# per-row distribution sampling (ref: multisample_op.cc _sample_uniform etc.)
@register("_sample_uniform", num_inputs=2, differentiable=False, needs_rng=True)
def _sample_uniform(low, high, shape=(), dtype="float32", rng=None):
    tgt = tuple(low.shape) + (tuple(shape) if shape else ())
    u = jax.random.uniform(rng, tgt, _dt(dtype))
    bshape = low.shape + (1,) * (len(tgt) - low.ndim)
    return low.reshape(bshape) + u * (high - low).reshape(bshape)


@register("_sample_normal", num_inputs=2, differentiable=False, needs_rng=True)
def _sample_normal(mu, sigma, shape=(), dtype="float32", rng=None):
    tgt = tuple(mu.shape) + (tuple(shape) if shape else ())
    z = jax.random.normal(rng, tgt, _dt(dtype))
    bshape = mu.shape + (1,) * (len(tgt) - mu.ndim)
    return mu.reshape(bshape) + z * sigma.reshape(bshape)
