"""Vision / detection operators.

TPU-native equivalents of the reference's custom-CUDA detection ops
(SURVEY §2.2 vision row): ROIPooling (src/operator/roi_pooling.cu),
MultiBoxPrior/Target/Detection (src/operator/contrib/multibox_*.cu),
Proposal (src/operator/contrib/proposal.cu), BilinearSampler /
GridGenerator / SpatialTransformer (src/operator/bilinear_sampler.cu,
grid_generator.cc, spatial_transformer.cu), Correlation
(src/operator/correlation.cu), Pad, box_nms (contrib/bounding_box.cc).

Design: everything is static-shape, batched, branch-free — gathers and
masked reductions instead of the reference's per-thread dynamic loops, so
XLA can tile onto the TPU. NMS is the classic O(N²) masked iteration with a
fixed trip count (`lax.fori_loop`), the standard TPU formulation.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG = -1e30


# ---------------------------------------------------------------------------
# ROI pooling (ref: src/operator/roi_pooling.cc/.cu)
# ---------------------------------------------------------------------------

@register("ROIPooling", num_inputs=2, nograd_inputs=(1,))
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max-pool each ROI to pooled_size (ref: roi_pooling.cc:roi 5-tuple
    [batch_idx, x1, y1, x2, y2])."""
    N, C, H, W = data.shape
    R = rois.shape[0]
    ph, pw = pooled_size

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bi]                                   # (C, H, W)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)

        def pool_bin(py, px):
            hstart = jnp.floor(y1 + py * bin_h)
            hend = jnp.ceil(y1 + (py + 1) * bin_h)
            wstart = jnp.floor(x1 + px * bin_w)
            wend = jnp.ceil(x1 + (px + 1) * bin_w)
            ymask = (ys >= hstart) & (ys < hend) & (ys >= 0) & (ys < H)
            xmask = (xs >= wstart) & (xs < wend) & (xs >= 0) & (xs < W)
            mask = ymask[:, None] & xmask[None, :]
            masked = jnp.where(mask[None], img, _NEG)
            val = masked.max(axis=(1, 2))
            return jnp.where(mask.any(), val, 0.0)

        py = jnp.arange(ph)
        px = jnp.arange(pw)
        out = jax.vmap(lambda y: jax.vmap(lambda x: pool_bin(y, x))(px))(py)
        return jnp.transpose(out, (2, 0, 1))             # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# MultiBox family (SSD; ref: src/operator/contrib/multibox_*.cc/.cu)
# ---------------------------------------------------------------------------

@register("MultiBoxPrior", num_inputs=1, differentiable=False,
          aliases=("_contrib_MultiBoxPrior",))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for one feature map (ref: multibox_prior.cc). Output
    (1, H*W*(num_sizes+num_ratios-1), 4) in corner format, normalized."""
    H, W = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    # anchors: sizes[0] with all ratios + other sizes with ratio 1
    ws, hs = [], []
    for r in ratios:
        ws.append(sizes[0] * np.sqrt(r))
        hs.append(sizes[0] / np.sqrt(r))
    for s in sizes[1:]:
        ws.append(s * np.sqrt(ratios[0]))
        hs.append(s / np.sqrt(ratios[0]))
    ws = jnp.asarray(ws, jnp.float32) / 2
    hs = jnp.asarray(hs, jnp.float32) / 2
    A = ws.shape[0]
    cxg, cyg = jnp.meshgrid(cx, cy)                     # (H, W)
    cxg = cxg.reshape(-1, 1)
    cyg = cyg.reshape(-1, 1)
    boxes = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
    boxes = boxes.reshape(1, H * W * A, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _box_iou_corner(a, b):
    """IoU matrix between (N,4) and (M,4) corner boxes."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("MultiBoxTarget", num_inputs=3, differentiable=False,
          num_outputs=3, aliases=("_contrib_MultiBoxTarget",))
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign anchors to ground truth (ref: multibox_target.cc). label is
    (B, M, 5) [cls, x1, y1, x2, y2] padded with -1 rows. Returns
    (loc_target (B, 4A), loc_mask (B, 4A), cls_target (B, A))."""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)

    def per_sample(lab, pred):
        valid = lab[:, 0] >= 0                          # (M,)
        gt = lab[:, 1:5]
        iou = _box_iou_corner(anchors, gt)              # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = iou.argmax(axis=1)                    # (A,)
        best_iou = iou.max(axis=1)
        # force-match: each valid gt claims its best anchor
        best_anchor = iou.argmax(axis=0)                # (M,)
        forced = jnp.zeros((A,), bool).at[best_anchor].set(valid)
        pos = forced | (best_iou >= overlap_threshold)
        matched_gt = gt[best_gt]                        # (A, 4)
        cls = jnp.where(pos, lab[best_gt, 0] + 1, 0.0)  # 0 = background
        if negative_mining_ratio > 0:
            # hard-negative mining (multibox_target.cc:216): unmatched
            # anchors whose best IoU stays BELOW the thresh (near-positives
            # are excluded from mining) compete for ratio×num_pos background
            # slots (>= the minimum), hardest first — hardness is a LOW
            # background softmax probability (the loss -log(bg_prob) the
            # reference skips the log of); every other negative is marked
            # ignore_label and must not reach the classification loss
            neg = ~pos
            bg_prob = jax.nn.softmax(pred, axis=0)[0]    # (A,)
            cand = neg & (best_iou < negative_mining_thresh)
            num_keep = jnp.maximum(
                negative_mining_ratio * jnp.sum(pos),
                float(minimum_negative_samples))
            score = jnp.where(cand, -bg_prob, -jnp.inf)  # hardest first
            order = jnp.argsort(-score)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(
                jnp.arange(A, dtype=jnp.int32))
            keep_neg = cand & (rank < num_keep)
            cls = jnp.where(neg & ~keep_neg, ignore_label, cls)
        # encode offsets (center form, variance-scaled)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(matched_gt[:, 2] - matched_gt[:, 0], 1e-8)
        gh = jnp.maximum(matched_gt[:, 3] - matched_gt[:, 1], 1e-8)
        gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
        gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / var[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / var[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / var[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)    # (A, 4)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        loc_m = jnp.broadcast_to(pos[:, None], (A, 4)).astype(jnp.float32)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls

    loc_target, loc_mask, cls_target = jax.vmap(per_sample)(label, cls_pred)
    return loc_target, loc_mask, cls_target


def _nms_fixed(boxes, scores, iou_threshold, max_out):
    """Static-shape NMS: iteratively pick max-score box, suppress overlaps.
    Returns indices (max_out,) with -1 padding."""
    N = boxes.shape[0]
    iou = _box_iou_corner(boxes, boxes)

    def body(i, state):
        alive_scores, picked = state
        best = jnp.argmax(alive_scores)
        best_score = alive_scores[best]
        valid = best_score > _NEG / 2
        picked = picked.at[i].set(jnp.where(valid, best, -1))
        suppress = iou[best] >= iou_threshold
        new_scores = jnp.where(suppress, _NEG, alive_scores)
        new_scores = new_scores.at[best].set(_NEG)
        return (jnp.where(valid, new_scores, alive_scores), picked)

    picked0 = jnp.full((max_out,), -1, jnp.int32)
    _, picked = lax.fori_loop(0, max_out, body, (scores, picked0))
    return picked


@register("MultiBoxDetection", num_inputs=3, differentiable=False,
          aliases=("_contrib_MultiBoxDetection",))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                        background_id=0, nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS (ref: multibox_detection.cc). Returns (B, A, 6)
    [cls_id, score, x1, y1, x2, y2], suppressed rows cls_id=-1."""
    B, num_cls, A = cls_prob.shape
    anchors = anchor.reshape(-1, 4)
    var = jnp.asarray(variances, jnp.float32)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    topk = A if nms_topk <= 0 else min(nms_topk, A)

    def per_sample(probs, loc):
        loc = loc.reshape(-1, 4)
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw / 2
        h = jnp.exp(loc[:, 3] * var[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # per-anchor best foreground class
        fg = jnp.concatenate([probs[:background_id],
                              probs[background_id + 1:]], axis=0)
        cls_id = fg.argmax(axis=0)                      # (A,) in fg space
        score = fg.max(axis=0)
        cls_id = jnp.where(cls_id >= background_id, cls_id + 1, cls_id) - 1 \
            if background_id == 0 else cls_id
        score = jnp.where(score > threshold, score, _NEG)
        keep = _nms_fixed(boxes, score, nms_threshold, topk)
        out = jnp.full((A, 6), -1.0)
        rows = jnp.arange(topk)
        sel = jnp.maximum(keep, 0)
        valid = keep >= 0
        entries = jnp.concatenate(
            [cls_id[sel][:, None].astype(jnp.float32),
             jnp.where(score[sel] > _NEG / 2, score[sel], 0.0)[:, None],
             boxes[sel]], axis=1)
        entries = jnp.where(valid[:, None], entries, -1.0)
        out = out.at[rows].set(entries)
        return out

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register("box_nms", num_inputs=1, differentiable=False,
          aliases=("_contrib_box_nms",))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
             score_index=1, id_index=-1, force_suppress=True, in_format="corner",
             out_format="corner"):
    """Generic NMS over (..., N, K) box tensors (ref: contrib/bounding_box.cc)."""
    shape = data.shape
    flat = data.reshape(-1, shape[-2], shape[-1])
    N = shape[-2]
    max_out = N if topk <= 0 else min(topk, N)

    def per_batch(d):
        boxes = d[:, coord_start:coord_start + 4]
        if in_format == "center":
            cx, cy, w, h = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
            boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                              axis=-1)
        scores = d[:, score_index]
        scores = jnp.where(scores > valid_thresh, scores, _NEG)
        keep = _nms_fixed(boxes, scores, overlap_thresh, max_out)
        out = jnp.full_like(d, -1.0)
        sel = jnp.maximum(keep, 0)
        valid = keep >= 0
        rows = jnp.arange(max_out)
        out = out.at[rows].set(jnp.where(valid[:, None], d[sel], -1.0))
        return out

    return jax.vmap(per_batch)(flat).reshape(shape)


@register("Proposal", num_inputs=3, differentiable=False,
          fnum_outputs=lambda p: 2 if p.get("output_score") else 1,
          aliases=("_contrib_Proposal", "_contrib_MultiProposal",
                   "MultiProposal"))
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
              output_score=False, iou_loss=False):
    """RPN proposal generation (ref: contrib/proposal.cc). Returns
    (B*post_nms, 5) rois [batch_idx, x1, y1, x2, y2]."""
    B, A2, H, W = cls_prob.shape
    A = A2 // 2
    base = feature_stride
    # base anchors centered in the first stride cell (ref: proposal.cc
    # GenerateAnchors)
    anchors = []
    ctr = (base - 1) / 2.0
    for r in ratios:
        w0 = np.round(np.sqrt(base * base / r))
        h0 = np.round(w0 * r)
        for s in scales:
            ws, hs = w0 * s, h0 * s
            anchors.append([ctr - (ws - 1) / 2, ctr - (hs - 1) / 2,
                            ctr + (ws - 1) / 2, ctr + (hs - 1) / 2])
    base_anchors = jnp.asarray(anchors, jnp.float32)     # (A, 4)
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shift_x, shift_y = jnp.meshgrid(sx, sy)
    shifts = jnp.stack([shift_x.ravel(), shift_y.ravel(),
                        shift_x.ravel(), shift_y.ravel()], axis=1)
    all_anchors = (base_anchors[None] + shifts[:, None]).reshape(-1, 4)
    n_total = all_anchors.shape[0]

    def per_sample(probs, deltas, info):
        # fg scores, anchor-minor layout to match all_anchors (HW, A)
        scores = probs[A:].transpose(1, 2, 0).reshape(-1)
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1
        acx = all_anchors[:, 0] + 0.5 * (aw - 1)
        acy = all_anchors[:, 1] + 0.5 * (ah - 1)
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
                           cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)], axis=-1)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, info[1] - 1),
                           jnp.clip(boxes[:, 1], 0, info[0] - 1),
                           jnp.clip(boxes[:, 2], 0, info[1] - 1),
                           jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=-1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        min_size = rpn_min_size * info[2]
        scores = jnp.where((ws >= min_size) & (hs >= min_size), scores, _NEG)
        pre = min(rpn_pre_nms_top_n, n_total)
        top_scores, order = lax.top_k(scores, pre)
        top_boxes = boxes[order]
        keep = _nms_fixed(top_boxes, top_scores, threshold,
                          rpn_post_nms_top_n)
        sel = jnp.maximum(keep, 0)
        valid = keep >= 0
        out_boxes = jnp.where(valid[:, None], top_boxes[sel], 0.0)
        out_scores = jnp.where(valid, top_scores[sel], 0.0)
        return out_boxes, out_scores

    boxes, scores = jax.vmap(per_sample)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(B, dtype=jnp.float32),
                           rpn_post_nms_top_n)
    rois = jnp.concatenate([batch_idx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


# ---------------------------------------------------------------------------
# Sampling ops (ref: bilinear_sampler.cc, grid_generator.cc,
# spatial_transformer.cc)
# ---------------------------------------------------------------------------

def _bilinear_gather(img, gx, gy):
    """Bilinear sample img (C,H,W) at pixel coords gx,gy (Ho,Wo)."""
    C, H, W = img.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1 = x0 + 1
    y1 = y0 + 1
    wx1 = gx - x0
    wy1 = gy - y0
    wx0 = 1 - wx1
    wy0 = 1 - wy1

    def at(y, x):
        inb = (x >= 0) & (x <= W - 1) & (y >= 0) & (y <= H - 1)
        xi = jnp.clip(x, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(y, 0, H - 1).astype(jnp.int32)
        v = img[:, yi, xi]                              # (C, Ho, Wo)
        return jnp.where(inb[None], v, 0.0)

    return (at(y0, x0) * (wy0 * wx0)[None] + at(y0, x1) * (wy0 * wx1)[None] +
            at(y1, x0) * (wy1 * wx0)[None] + at(y1, x1) * (wy1 * wx1)[None])


@register("BilinearSampler", num_inputs=2)
def _bilinear_sampler(data, grid):
    """ref: bilinear_sampler.cc — grid (B, 2, Ho, Wo) in [-1, 1]."""
    B, C, H, W = data.shape

    def one(img, g):
        gx = (g[0] + 1) * (W - 1) / 2
        gy = (g[1] + 1) * (H - 1) / 2
        return _bilinear_gather(img, gx, gy)

    return jax.vmap(one)(data, grid)


@register("GridGenerator", num_inputs=1)
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """ref: grid_generator.cc — affine (B,6) → sampling grid (B,2,H,W),
    or warp (B,2,H,W) flow → grid."""
    if transform_type == "affine":
        B = data.shape[0]
        H, W = target_shape
        xs = jnp.linspace(-1, 1, W)
        ys = jnp.linspace(-1, 1, H)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)

        def one(theta):
            t = theta.reshape(2, 3)
            out = t @ coords                            # (2, HW)
            return out.reshape(2, H, W)

        return jax.vmap(one)(data)
    # warp: data is flow (B, 2, H, W) in pixels
    B, _, H, W = data.shape
    xs = jnp.arange(W, dtype=jnp.float32)
    ys = jnp.arange(H, dtype=jnp.float32)
    gx, gy = jnp.meshgrid(xs, ys)
    nx = (gx[None] + data[:, 0]) * 2 / max(W - 1, 1) - 1
    ny = (gy[None] + data[:, 1]) * 2 / max(H - 1, 1) - 1
    return jnp.stack([nx, ny], axis=1)


@register("SpatialTransformer", num_inputs=2)
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=False):
    """ref: spatial_transformer.cc — affine loc net + bilinear sampling."""
    grid = _grid_generator(loc, "affine", target_shape)
    return _bilinear_sampler(data, grid)


@register("Correlation", num_inputs=2)
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """ref: correlation.cc — patch cross-correlation between two feature
    maps (FlowNet)."""
    B, C, H, W = data1.shape
    d = max_displacement
    pad = pad_size
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            shifted = jnp.roll(p2, (-dy, -dx), axis=(2, 3))
            if is_multiply:
                prod = (p1 * shifted).mean(axis=1)
            else:
                prod = -jnp.abs(p1 - shifted).mean(axis=1)
            outs.append(prod)
    out = jnp.stack(outs, axis=1)
    if pad:
        out = out[:, :, pad:-pad, pad:-pad]
    return out[:, :, ::stride1, ::stride1]


# NOTE: "Pad" is registered once, in tensor.py (graftlint GL107 caught the
# duplicate registration that used to live here: it silently shadowed the
# canonical op for the "Pad" spelling while "pad" kept the original).


@register("Crop", num_inputs=None)
def _crop(*inputs, offset=(0, 0), h_w=(0, 0), num_args=1, center_crop=False):
    """ref: src/operator/crop.cc — crop first input to shape of second (or
    h_w)."""
    data = inputs[0]
    if num_args == 2 and len(inputs) > 1:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = h_w
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        y0 = (H - th) // 2
        x0 = (W - tw) // 2
    else:
        y0, x0 = offset
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("ROIAlign", num_inputs=2, nograd_inputs=(1,),
          aliases=("_contrib_ROIAlign",))
def _roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sample_ratio=2):
    """ROI Align (bilinear, no quantization) — modern companion to
    ROIPooling; the reference era used ROIPooling, Mask-RCNN needs this."""
    N, C, H, W = data.shape
    ph, pw = pooled_size
    sr = max(int(sample_ratio), 1)

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[1] * spatial_scale, roi[2] * spatial_scale,
                          roi[3] * spatial_scale, roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bi]
        # sample sr×sr points per bin, average
        iy = jnp.arange(ph * sr, dtype=jnp.float32)
        ix = jnp.arange(pw * sr, dtype=jnp.float32)
        gy = y1 + (iy + 0.5) * bin_h / sr
        gx = x1 + (ix + 0.5) * bin_w / sr
        gxx, gyy = jnp.meshgrid(gx, gy)
        vals = _bilinear_gather(img, gxx, gyy)          # (C, ph*sr, pw*sr)
        vals = vals.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))
        return vals

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# Deformable ops + position-sensitive ROI pooling (R-FCN / Deformable
# ConvNets; ref: src/operator/contrib/deformable_convolution.cc,
# psroi_pooling.cc, deformable_psroi_pooling.cc — custom CUDA in the
# reference, vectorized XLA gathers + one MXU matmul here)
# ---------------------------------------------------------------------------

def _deform_conv_param_shapes(data_shape, params):
    """offset comes from a sibling conv, so only weight/bias back-fill."""
    nf = params.get("num_filter", 0)
    ng = params.get("num_group", 1)
    kernel = tuple(params.get("kernel", ()))
    return {"weight": (nf, data_shape[1] // ng) + kernel, "bias": (nf,)}


def _deform_argnames(params):
    if params.get("no_bias", False):
        return ("data", "offset", "weight")
    return ("data", "offset", "weight", "bias")


@register("_contrib_DeformableConvolution", num_inputs=None,
          fargnames=_deform_argnames,
          finfer_params=_deform_conv_param_shapes,
          aliases=("DeformableConvolution",))
def _deformable_convolution(*args, kernel=(), stride=(), dilate=(), pad=(),
                            num_filter=0, num_group=1,
                            num_deformable_group=1, workspace=1024,
                            no_bias=False, layout=None):
    """Deformable convolution v1 (ref: deformable_convolution-inl.h).

    offset has 2·DG·kh·kw channels laid out [dg][tap][y,x] over the output
    grid.  Implementation: deformable im2col via vectorized bilinear
    gathers (one per kernel tap — a static python loop of kh·kw), then the
    contraction runs as a single batched matmul on the MXU — the same
    im2col+gemm structure as the reference's CUDA path
    (deformable_im2col.cuh), with XLA owning the gather fusion.
    """
    if no_bias:
        data, offset, weight = args
        bias = None
    else:
        data, offset, weight, bias = args
    N, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride if stride else (1, 1)
    dh, dw = dilate if dilate else (1, 1)
    ph, pw = pad if pad else (0, 0)
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    DG = num_deformable_group
    G = num_group
    base_y = jnp.arange(Ho, dtype=jnp.float32) * sh - ph      # (Ho,)
    base_x = jnp.arange(Wo, dtype=jnp.float32) * sw - pw      # (Wo,)

    def per_image(img, off):
        off = off.reshape(DG, kh * kw, 2, Ho, Wo)
        img_g = img.reshape(DG, C // DG, H, W)
        taps = []
        for k in range(kh * kw):
            i, j = divmod(k, kw)
            per_dg = []
            for dg in range(DG):
                gy = base_y[:, None] + i * dh + off[dg, k, 0]
                gx = base_x[None, :] + j * dw + off[dg, k, 1]
                per_dg.append(_bilinear_gather(img_g[dg], gx, gy))
            taps.append(jnp.concatenate(per_dg, axis=0))  # (C, Ho, Wo)
        # (C, K, Ho*Wo) im2col buffer
        col = jnp.stack(taps, axis=1).reshape(C, kh * kw, Ho * Wo)
        col = col.reshape(G, (C // G) * kh * kw, Ho * Wo)
        wmat = weight.reshape(G, num_filter // G, (C // G) * kh * kw)
        out = jnp.einsum("gfk,gkp->gfp", wmat, col,
                         preferred_element_type=jnp.float32)
        return out.reshape(num_filter, Ho, Wo)

    out = jax.vmap(per_image)(data, offset)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register("_contrib_PSROIPooling", num_inputs=2, nograd_inputs=(1,),
          aliases=("PSROIPooling",))
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                   pooled_size=0, group_size=0):
    """Position-sensitive ROI pooling (ref: psroi_pooling.cu kernel).

    data (N, output_dim·gs², H, W); rois (R, 5); out (R, output_dim,
    k, k) — bin (ph, pw) averages channel (ctop·gs + gh)·gs + gw over its
    spatial extent.  Dynamic ROI bounds become masks over the full map
    (the ROIPooling trick above), keeping shapes static for XLA.
    """
    N, Cc, H, W = data.shape
    k = int(pooled_size)
    gs = int(group_size) if group_size else k
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    # channel index per (ctop, ph, pw): static table
    ctop = np.arange(output_dim)[:, None, None]
    gh = np.minimum(np.maximum((np.arange(k) * gs) // k, 0), gs - 1)
    chan = jnp.asarray(((ctop * gs + gh[None, :, None]) * gs
                        + gh[None, None, :]).astype(np.int32))  # (od, k, k)

    def one_roi(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / k
        bin_w = rw / k
        img = data[bi]                                    # (Cc, H, W)

        def pool_bin(co, py, px):
            hstart = jnp.clip(jnp.floor(py * bin_h + y1), 0, H)
            hend = jnp.clip(jnp.ceil((py + 1) * bin_h + y1), 0, H)
            wstart = jnp.clip(jnp.floor(px * bin_w + x1), 0, W)
            wend = jnp.clip(jnp.ceil((px + 1) * bin_w + x1), 0, W)
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                    & (xs[None, :] >= wstart) & (xs[None, :] < wend))
            cnt = mask.sum()
            # only the bin's position-sensitive channel is reduced
            s = jnp.where(mask, img[chan[co, py, px]], 0.0).sum()
            return jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), 0.0)

        cos = jnp.arange(output_dim)
        bins = jnp.arange(k)
        return jax.vmap(lambda co: jax.vmap(lambda py: jax.vmap(
            lambda px: pool_bin(co, py, px))(bins))(bins))(cos)

    return jax.vmap(one_roi)(rois)


def _dpsroi_argnames(params):
    if params.get("no_trans", False):
        return ("data", "rois")
    return ("data", "rois", "trans")


@register("_contrib_DeformablePSROIPooling", num_inputs=None,
          num_outputs=2, num_visible_outputs=1,
          fargnames=_dpsroi_argnames, nograd_inputs=(1,),
          aliases=("DeformablePSROIPooling",))
def _deformable_psroi_pooling(*args, spatial_scale=1.0, output_dim=0,
                              group_size=0, pooled_size=0, part_size=0,
                              sample_per_part=1, trans_std=0.0,
                              no_trans=False):
    """Deformable position-sensitive ROI pooling
    (ref: deformable_psroi_pooling.cu DeformablePSROIPoolForwardKernel).

    Each bin's sampling window shifts by a learned normalized offset from
    ``trans`` (shape (R, 2·num_classes, part, part)); sample_per_part²
    points are bilinearly sampled and averaged.  Outputs (out, top_count)
    like the reference (top_count feeds its backward pass; here autograd
    differentiates the sampling directly and top_count is aux).
    """
    if no_trans:
        data, rois = args
        trans = None
    else:
        data, rois, trans = args
    N, Cc, H, W = data.shape
    k = int(pooled_size)
    gs = int(group_size) if group_size else k
    part = int(part_size) if part_size else k
    spp = max(int(sample_per_part), 1)
    num_classes = 1 if no_trans else trans.shape[1] // 2
    chan_per_class = output_dim // num_classes

    ctop = np.arange(output_dim)[:, None, None]
    gh = np.minimum(np.maximum((np.arange(k) * gs) // k, 0), gs - 1)
    chan = jnp.asarray(((ctop * gs + gh[None, :, None]) * gs
                        + gh[None, None, :]).astype(np.int32))  # (od, k, k)
    part_of = jnp.asarray(np.floor(np.arange(k) / k * part).astype(np.int32))
    class_of = jnp.asarray((np.arange(output_dim)
                            // chan_per_class).astype(np.int32))

    def one_roi(roi, tr):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / k, rw / k
        sub_h, sub_w = bin_h / spp, bin_w / spp
        img = data[bi]

        def pool_one(co, py, px):
            cls = class_of[co]
            if no_trans:
                tx = ty = jnp.float32(0.0)
            else:
                tx = tr[2 * cls, part_of[py], part_of[px]] * trans_std
                ty = tr[2 * cls + 1, part_of[py], part_of[px]] * trans_std
            hstart = py.astype(jnp.float32) * bin_h + y1 + ty * rh
            wstart = px.astype(jnp.float32) * bin_w + x1 + tx * rw
            iy = jnp.arange(spp, dtype=jnp.float32)
            hh = hstart + iy * sub_h                     # (spp,)
            ww = wstart + iy * sub_w
            hgrid, wgrid = jnp.meshgrid(hh, ww, indexing="ij")
            valid = ((wgrid > -0.5) & (wgrid < W - 0.5)
                     & (hgrid > -0.5) & (hgrid < H - 0.5))
            hs = jnp.clip(hgrid, 0.0, H - 1.0)
            wsx = jnp.clip(wgrid, 0.0, W - 1.0)
            vals = _bilinear_gather(img[chan[co, py, px]][None], wsx, hs)[0]
            cnt = valid.sum()
            s = jnp.where(valid, vals, 0.0).sum()
            return (jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), 0.0),
                    cnt.astype(jnp.float32))

        cos = jnp.arange(output_dim)
        bins = jnp.arange(k)
        return jax.vmap(lambda co: jax.vmap(lambda py: jax.vmap(
            lambda px: pool_one(co, py, px))(bins))(bins))(cos)

    if trans is None:
        dummy = jnp.zeros((rois.shape[0], 2, part, part), jnp.float32)
        out, cnt = jax.vmap(one_roi)(rois, dummy)
    else:
        out, cnt = jax.vmap(one_roi)(rois, trans)
    return out, cnt
