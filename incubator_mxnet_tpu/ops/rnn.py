"""Fused multi-layer RNN operator.

TPU-native equivalent of the reference's fused RNN op
(src/operator/rnn-inl.h RNNParam; GPU path src/operator/cudnn_rnn-inl.h:152
cudnnRNNForwardTraining): modes rnn_relu / rnn_tanh / lstm / gru,
multi-layer, bidirectional, inter-layer dropout.

Design: one ``lax.scan`` over time per layer — the h2h matmul stays on the
MXU every step, XLA pipelines the scan; no per-step Python. Gate math
matches the reference cell definitions exactly (rnn_cell.py LSTMCell/GRUCell
slicing order: LSTM [i, f, c, o], GRU [r, z, n]) so fused and unrolled paths
are numerically interchangeable, as in the reference.

Input layout TNC (seq, batch, feature) like the reference op; weights arrive
as separate i2h/h2h weight/bias arrays per layer+direction in the same order
the reference packs its flat parameter blob (rnn-inl.h):
  for layer in layers: for dir in dirs: W_i2h, W_h2h
  then            : for layer in layers: for dir in dirs: b_i2h, b_h2h
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode):
    if mode == "rnn_relu":
        def step(x_proj, h, c, w_hh, b_hh):
            new_h = jax.nn.relu(x_proj + h @ w_hh.T + b_hh)
            return new_h, c
    elif mode == "rnn_tanh":
        def step(x_proj, h, c, w_hh, b_hh):
            new_h = jnp.tanh(x_proj + h @ w_hh.T + b_hh)
            return new_h, c
    elif mode == "lstm":
        def step(x_proj, h, c, w_hh, b_hh):
            gates = x_proj + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
    elif mode == "gru":
        def step(x_proj, h, c, w_hh, b_hh):
            hp = h @ w_hh.T + b_hh
            xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            new_h = (1 - z) * n + z * h
            return new_h, c
    else:
        raise ValueError("unknown RNN mode %r" % mode)
    return step


def _layer_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, reverse=False):
    """Run one direction of one layer over the full sequence.

    The i2h projection for ALL timesteps is one big matmul (seq*batch, in) ×
    (in, gates*H) — maximal MXU utilization; the scan carries only the h2h
    recurrence."""
    step = _cell_step(mode)
    x_proj = x @ w_ih.T + b_ih            # (T, N, gates*H)

    def body(carry, xp):
        h, c = carry
        new_h, new_c = step(xp, h, c, w_hh, b_hh)
        return (new_h, new_c), new_h

    (hT, cT), ys = lax.scan(body, (h0, c0), x_proj, reverse=reverse)
    return ys, hT, cT


def _rnn_argnames(p):
    """Named inputs in the op's positional order (data, states, then
    layer-major/dir-inner weight+bias arrays — rnn-inl.h packing order)."""
    mode = p.get("mode", "lstm")
    layers = int(p.get("num_layers", 1))
    dirs = 2 if p.get("bidirectional") else 1
    names = ["data", "state"] + (["state_cell"] if mode == "lstm" else [])
    prefixes = ["%s%d" % ("lr"[d], l) for l in range(layers)
                for d in range(dirs)]
    for pre in prefixes:
        names += ["%s_i2h_weight" % pre, "%s_h2h_weight" % pre]
    for pre in prefixes:
        names += ["%s_i2h_bias" % pre, "%s_h2h_bias" % pre]
    return names


def _rnn_param_shapes(data_shape, p):
    """Back-fill weight shapes from the TNC data shape (ref: rnn-inl.h
    RNNParam inferring the fused blob size)."""
    mode = p.get("mode", "lstm")
    gates = _GATES[mode]
    h = int(p.get("state_size", 0))
    layers = int(p.get("num_layers", 1))
    dirs = 2 if p.get("bidirectional") else 1
    c = data_shape[2]
    shapes = {}
    for l in range(layers):
        in_dim = c if l == 0 else dirs * h
        for d in range(dirs):
            pre = "%s%d" % ("lr"[d], l)
            shapes["%s_i2h_weight" % pre] = (gates * h, in_dim)
            shapes["%s_h2h_weight" % pre] = (gates * h, h)
            shapes["%s_i2h_bias" % pre] = (gates * h,)
            shapes["%s_h2h_bias" % pre] = (gates * h,)
    n_states = layers * dirs
    shapes["state"] = (n_states, data_shape[1], h)
    shapes["state_cell"] = (n_states, data_shape[1], h)
    return shapes


@register("RNN", num_inputs=None, needs_rng=True, takes_is_train=True,
          num_outputs=3, fargnames=_rnn_argnames,
          finfer_params=_rnn_param_shapes,
          fvisible=lambda p, n: n if p.get("state_outputs") else 1)
def _rnn(*inputs, state_size=0, num_layers=1, bidirectional=False, mode="lstm",
         p=0.0, state_outputs=False, lstm_state_clip_min=None,
         lstm_state_clip_max=None, rng=None, is_train=False):
    """ref: src/operator/rnn.cc (fused RNN); returns (out, hy, cy)."""
    dirs = 2 if bidirectional else 1
    is_lstm = mode == "lstm"
    data = inputs[0]
    hx = inputs[1]
    idx = 2
    if is_lstm:
        cx = inputs[idx]
        idx += 1
    else:
        cx = jnp.zeros_like(hx)
    n_mats = num_layers * dirs
    w_ih = inputs[idx:idx + 2 * n_mats:2]
    w_hh = inputs[idx + 1:idx + 2 * n_mats:2]
    idx += 2 * n_mats
    b_ih = inputs[idx:idx + 2 * n_mats:2]
    b_hh = inputs[idx + 1:idx + 2 * n_mats:2]

    x = data
    hy, cy = [], []
    k = rng
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            li = layer * dirs + d
            ys, hT, cT = _layer_scan(x, hx[li], cx[li], w_ih[li], w_hh[li],
                                     b_ih[li], b_hh[li], mode, reverse=d == 1)
            if is_lstm and lstm_state_clip_min is not None:
                cT = jnp.clip(cT, lstm_state_clip_min, lstm_state_clip_max)
            outs.append(ys)
            hy.append(hT)
            cy.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if is_train and p > 0.0 and layer < num_layers - 1:
            k, sub = jax.random.split(k)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
    return x, jnp.stack(hy), jnp.stack(cy)


# ---------------------------------------------------------------------------
# CTC loss (ref: src/operator/contrib/ctc_loss.cc — embedded warp-ctc;
# here: log-space alpha recursion as one lax.scan over time, batched by vmap)
# ---------------------------------------------------------------------------

_NEG_INF = -1e30


def _ctc_single(log_probs, ext, ext_len, data_len):
    """Negative log-likelihood for one sample.

    log_probs: (T, C) log-softmax scores; ext: (S,) extended label sequence
    (blank interleaved, padded); ext_len: true extended length; data_len:
    true input length."""
    T, C = log_probs.shape
    S = ext.shape[0]
    s_idx = jnp.arange(S)
    valid = s_idx < ext_len

    # alpha_0
    a0 = jnp.full((S,), _NEG_INF)
    a0 = a0.at[0].set(log_probs[0, ext[0]])
    a0 = a0.at[1].set(jnp.where(ext_len > 1, log_probs[0, ext[1]], _NEG_INF))

    same_as_2back = jnp.concatenate(
        [jnp.array([True, True]), ext[2:] == ext[:-2]])

    def step(alpha, lp):
        shift1 = jnp.concatenate([jnp.array([_NEG_INF]), alpha[:-1]])
        shift2 = jnp.concatenate([jnp.full((2,), _NEG_INF), alpha[:-2]])
        shift2 = jnp.where(same_as_2back, _NEG_INF, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + lp[ext]
        new_alpha = jnp.where(valid, new_alpha, _NEG_INF)
        return new_alpha, new_alpha

    _, alphas = lax.scan(step, a0, log_probs[1:])
    alphas = jnp.concatenate([a0[None], alphas])          # (T, S)
    a_last = alphas[jnp.maximum(data_len - 1, 0)]
    ll = jnp.logaddexp(a_last[jnp.maximum(ext_len - 1, 0)],
                       a_last[jnp.maximum(ext_len - 2, 0)])
    return -ll


@register("CTCLoss", num_inputs=None,
          aliases=("ctc_loss", "_contrib_CTCLoss", "_contrib_ctc_loss"))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="first"):
    """ref: src/operator/contrib/ctc_loss.cc. data (T,N,C) activations
    (softmax applied internally, as the reference does); label (N,L),
    padded with 0 ('first') / -1 ('last')."""
    T, N, C = data.shape
    log_probs = jax.nn.log_softmax(data, axis=-1)
    label = label.astype(jnp.int32)
    if blank_label == "first":
        blank = 0
        lab_valid = label > 0
        lab = label
    else:
        blank = C - 1
        lab_valid = label >= 0
        lab = jnp.where(lab_valid, label, 0)
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = lab_valid.sum(axis=1).astype(jnp.int32)
    if use_data_lengths and data_lengths is not None:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((N,), T, jnp.int32)

    L = label.shape[1]
    S = 2 * L + 1
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((N, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    ext_len = 2 * lab_len + 1

    return jax.vmap(_ctc_single, in_axes=(1, 0, 0, 0))(
        log_probs, ext, ext_len, dat_len)
