"""Attention kernels: Pallas flash attention + reference path.

The reference framework predates transformer attention entirely (SURVEY
§2.4: sequence handling = bucketing + fused RNN). These kernels are the
*new capability* SURVEY §7 phase 11 mandates: long-context attention that
maps onto the MXU with O(seq) memory.

* ``flash_attention`` — tiled online-softmax attention as a Pallas TPU
  kernel (one (block_q × d) Q tile resident in VMEM; K/V streamed in
  block_k tiles; running max/sum rescaling). Grid = (batch*heads,
  seq_q/block_q); the K loop is a fori_loop inside the kernel so the MXU
  sees back-to-back (block_q×d)·(d×block_k) matmuls.
* On non-TPU backends (the CPU test mesh) the same math runs as jnp — the
  kernel is numerics-identical by construction and tested against it.
* Registered as op ``_contrib_FlashAttention`` so both eager NDArray code
  and Symbol graphs can call it (one registry, two modes).
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# reference (jnp) attention — also the CPU path and the vjp recompute
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, causal=False, scale=None):
    """(B, H, Sq, D), (B, H, Sk, D) → (B, H, Sq, D)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(probs.dtype)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, sk, causal, scale,
                  block_q):
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32) * scale              # (bq, d)
    bq, d = q.shape
    num_kb = sk // block_k
    q_blk = pl.program_id(1)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                    # (bq, bk)
        if causal:
            q_pos = q_blk * block_q + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + p @ v_blk
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_forward_pallas(q, k, v, causal, scale, block_q=128, block_k=128):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)
    grid = (B * H, Sq // block_q)
    kernel = functools.partial(_flash_kernel, block_k=block_k, sk=Sk,
                               causal=causal, scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Sq * Sk * D,
            bytes_accessed=(qf.size + kf.size + vf.size) * 4,
            transcendentals=B * H * Sq * Sk),
    )(qf, kf, vf)
    return out.reshape(B, H, Sq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """softmax(QKᵀ·scale)·V with O(seq) memory.

    Pallas kernel on TPU; numerics-identical jnp path elsewhere. Backward
    recomputes attention (flash-style rematerialization) instead of storing
    the (Sq×Sk) probability matrix.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if jax.default_backend() == "tpu" and q.shape[2] % 128 == 0 and \
            k.shape[2] % 128 == 0 and q.shape[-1] % 128 == 0:
        return _flash_forward_pallas(q, k, v, causal, scale)
    return _attention_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    out = flash_attention(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def f(q_, k_, v_):
        return _attention_reference(q_, k_, v_, causal, scale)

    _, vjp_fn = jax.vjp(f, q, k, v)
    return vjp_fn(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@register("_contrib_FlashAttention", num_inputs=3,
          aliases=("flash_attention", "_contrib_DotProductAttention"))
def _flash_attention_op(q, k, v, causal=False, scale=None):
    """Registered op wrapper — (B, H, S, D) inputs."""
    return flash_attention(q, k, v, causal, scale)
