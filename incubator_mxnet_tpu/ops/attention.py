"""Attention kernels: Pallas flash attention + reference path.

The reference framework predates transformer attention entirely (SURVEY
§2.4: sequence handling = bucketing + fused RNN). These kernels are the
*new capability* SURVEY §7 phase 11 mandates: long-context attention that
maps onto the MXU with O(seq) memory.

* ``flash_attention`` — tiled online-softmax attention as a Pallas TPU
  kernel (one (block_q × d) Q tile resident in VMEM; K/V streamed in
  block_k tiles; running max/sum rescaling). Grid = (batch*heads,
  seq_q/block_q); the K loop is a fori_loop inside the kernel so the MXU
  sees back-to-back (block_q×d)·(d×block_k) matmuls.
* On non-TPU backends (the CPU test mesh) the same math runs as jnp — the
  kernel is numerics-identical by construction and tested against it.
* Registered as op ``_contrib_FlashAttention`` so both eager NDArray code
  and Symbol graphs can call it (one registry, two modes).
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# reference (jnp) attention — also the CPU path and the vjp recompute
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, causal=False, scale=None):
    """(B, H, Sq, D), (B, H, Sk, D) → (B, H, Sq, D)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(probs.dtype)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, sq, sk, causal,
                  scale, block_q):
    from jax.experimental import pallas as pl
    q = q_ref[0].astype(jnp.float32) * scale              # (bq, d)
    bq, d = q.shape
    num_kb = sk // block_k
    q_blk = pl.program_id(1)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        # full f32 MXU passes — the default matmul precision on TPU is bf16,
        # which is not acceptable for softmax logits
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            precision=lax.Precision.HIGHEST)   # (bq, bk)
        if causal:
            # query row r may see keys up to r + (sk - sq): the diagonal is
            # anchored at the *end* of the key axis, matching the jnp path's
            # tril(k=sk-sq) — essential for KV-cache decode where Sq != Sk
            q_pos = q_blk * block_q + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos + (sk - sq) >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_forward_pallas(q, k, v, causal, scale, block_q=128, block_k=128):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    # MXU lanes want D in multiples of 128; typical head dims (64, 96) get
    # zero-padded — padded Q columns contribute nothing to QKᵀ and padded V
    # columns produce output columns we slice off
    Dp = -(-D // 128) * 128
    if Dp != D:
        pad = [(0, 0)] * 3 + [(0, Dp - D)]
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    qf = q.reshape(B * H, Sq, Dp)
    kf = k.reshape(B * H, Sk, Dp)
    vf = v.reshape(B * H, Sk, Dp)
    grid = (B * H, Sq // block_q)
    kernel = functools.partial(_flash_kernel, block_k=block_k, sq=Sq, sk=Sk,
                               causal=causal, scale=scale, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, Dp), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, Dp), q.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Sq * Sk * Dp,
            bytes_accessed=(qf.size + kf.size + vf.size) * 4,
            transcendentals=B * H * Sq * Sk),
    )(qf, kf, vf)
    return out.reshape(B, H, Sq, Dp)[..., :D]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """softmax(QKᵀ·scale)·V with O(seq) memory.

    Pallas kernel on TPU; numerics-identical jnp path elsewhere. Backward
    recomputes attention (flash-style rematerialization) instead of storing
    the (Sq×Sk) probability matrix.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if jax.default_backend() == "tpu" and q.shape[2] % 128 == 0 and \
            k.shape[2] % 128 == 0:
        return _flash_forward_pallas(q, k, v, causal, scale)
    return _attention_reference(q, k, v, causal, scale)


def _kv_block_size(sk):
    """Largest power-of-two K-chunk ≤1024 dividing sk (else no chunking)."""
    for b in (1024, 512, 256, 128, 64):
        if sk % b == 0:
            return b
    return sk


def _flash_fwd(q, k, v, causal, scale):
    out = flash_attention(q, k, v, causal, scale)
    return out, (q, k, v, out)


def _flash_bwd(causal, scale, res, g):
    """Flash-style backward: two chunked passes over the key axis, never
    materializing the (Sq × Sk) score matrix — backward memory matches the
    forward's O(Sq · block) profile.

    Pass 1 recovers the softmax log-normalizer with an online max/sum scan;
    pass 2 rebuilds each probability tile from (logits − lse) and
    accumulates dQ (carried) and per-tile dK/dV (scan outputs).
    """
    q, k, v = res[0], res[1], res[2]
    out = res[3]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dtype_in = q.dtype
    Sq, Sk = q.shape[2], k.shape[2]
    block = _kv_block_size(Sk)
    nb = Sk // block
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    kb = k.astype(jnp.float32).reshape(*k.shape[:2], nb, block, k.shape[-1])
    vb = v.astype(jnp.float32).reshape(*v.shape[:2], nb, block, v.shape[-1])
    kb = jnp.moveaxis(kb, 2, 0)                       # (nb, B, H, blk, D)
    vb = jnp.moveaxis(vb, 2, 0)
    q_pos = jnp.arange(Sq)[:, None] + (Sk - Sq)       # diag anchored at end

    hi = jax.lax.Precision.HIGHEST  # bf16 MXU passes would desync p from out

    def scores(k_blk, i):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk, precision=hi,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = i * block + jnp.arange(block)[None, :]
            mask = q_pos >= k_pos
            return jnp.where(mask, s, _NEG_INF), mask
        return s, None

    def stat_step(carry, xs):
        m_prev, l_prev = carry
        k_blk, i = xs
        s, _ = scores(k_blk, i)
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        l_new = l_prev * jnp.exp(m_prev - m_new) + \
            jnp.exp(s - m_new[..., None]).sum(axis=-1)
        return (m_new, l_new), None

    m0 = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    (m, l), _ = lax.scan(stat_step, (m0, l0), (kb, jnp.arange(nb)))
    # keep (m, l) separate: folding into m + log(l) loses log(l) to float
    # absorption when m is the -1e30 sentinel (rows with no visible keys)
    l_inv = 1.0 / jnp.maximum(l, 1e-20)
    delta = (gf * out.astype(jnp.float32)).sum(-1)    # (B, H, Sq)

    def grad_step(dq_acc, xs):
        k_blk, v_blk, i = xs
        s, mask = scores(k_blk, i)
        p = jnp.exp(s - m[..., None]) * l_inv[..., None]
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, gf, precision=hi)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_blk, precision=hi)
        ds = p * (dp - delta[..., None]) * scale
        if mask is not None:
            # masked logits are constants in the forward (`where` routes the
            # gradient around them), so they carry no dQ/dK — matters for
            # rows with no visible keys, where p is uniform, not 0
            ds = jnp.where(mask, ds, 0.0)
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk,
                                     precision=hi)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf, precision=hi)
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dk, dv) = lax.scan(grad_step, dq0, (kb, vb, jnp.arange(nb)))
    dk = jnp.moveaxis(dk, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dv, 0, 2).reshape(v.shape)
    return (dq.astype(dtype_in), dk.astype(k.dtype), dv.astype(v.dtype))


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@register("_contrib_FlashAttention", num_inputs=3,
          aliases=("flash_attention", "_contrib_DotProductAttention"))
def _flash_attention_op(q, k, v, causal=False, scale=None):
    """Registered op wrapper — (B, H, S, D) inputs."""
    return flash_attention(q, k, v, causal, scale)


# graftlint: disable=GL302 -- `eager` is a host are-we-staging bool from dispatch_on_mesh, not a traced value; branching on it is the point
@register("_contrib_RingAttention", num_inputs=3, no_jit=True,
          aliases=("ring_attention",))
def _ring_attention_op(q, k, v, seq_axis="sp", causal=False, scale=None):
    """Exact attention over sequence shards (B, H, S, D): S is sharded on
    the mesh axis ``seq_axis`` and K/V blocks rotate over ICI
    (parallel/ring_attention.py).  The mesh comes from the enclosing
    ``parallel.use_mesh`` scope — the op itself stays array-in/array-out
    like every registry op.  The modern capability mandated over the
    reference's bucketing story (SURVEY §5.7)."""
    from ..parallel.mesh import current_mesh
    mesh = current_mesh(required=True)
    if seq_axis not in mesh.axis_names:
        raise ValueError("mesh %s has no axis %r for ring attention"
                         % (mesh.axis_names, seq_axis))
    from ..parallel.ring_attention import ring_attention
    from ..parallel.mesh import dispatch_on_mesh, gather_home
    from jax.sharding import PartitionSpec
    spec = PartitionSpec(None, None, seq_axis, None)
    out, eager = dispatch_on_mesh(
        lambda a, b, c: ring_attention(a, b, c, mesh, seq_axis, causal,
                                       scale),
        mesh, (spec, spec, spec), q, k, v)
    # staging (inside e.g. the DataParallelTrainer step over a dp×sp
    # mesh): output STAYS sequence-sharded; eager: gather home so
    # downstream single-device ops see a plain array
    return gather_home(out, mesh) if eager else out
