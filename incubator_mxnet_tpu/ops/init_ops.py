"""Creation operators (ref: src/operator/tensor/init_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("_zeros", num_inputs=0, differentiable=False, aliases=("zeros",))
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, jnp.dtype(dtype))


@register("_ones", num_inputs=0, differentiable=False, aliases=("ones",))
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, jnp.dtype(dtype))


@register("_full", num_inputs=0, differentiable=False, aliases=("full",))
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, jnp.dtype(dtype))


@register("_arange", num_inputs=0, differentiable=False, aliases=("arange",))
def _arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False, dtype="float32"):
    out = jnp.arange(start, stop, step, jnp.dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", num_inputs=0, differentiable=False, aliases=("eye",))
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=jnp.dtype(dtype))


@register("_linspace", num_inputs=0, differentiable=False, aliases=("linspace",))
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=jnp.dtype(dtype))


@register("zeros_like", num_inputs=1, differentiable=False)
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", num_inputs=1, differentiable=False)
def _ones_like(data):
    return jnp.ones_like(data)


@register("shape_array", num_inputs=1, differentiable=False)
def _shape_array(data):
    """ref: elemwise_unary_op_basic.cc shape_array"""
    return jnp.asarray(data.shape, dtype=jnp.int64)


@register("size_array", num_inputs=1, differentiable=False)
def _size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int64)
