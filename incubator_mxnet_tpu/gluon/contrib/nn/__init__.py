"""Contrib neural-network layers
(ref: python/mxnet/gluon/contrib/nn/__init__.py).
"""
from .basic_layers import *
from . import basic_layers
