"""Contrib layers: parallel composition + identity
(ref: python/mxnet/gluon/contrib/nn/basic_layers.py).
"""
from __future__ import annotations

from ...nn.basic_layers import Sequential, HybridSequential
from ...block import HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity"]


class Concurrent(Sequential):
    """Feed the input to every child; concatenate outputs on ``axis``
    (ref: basic_layers.py Concurrent:27)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        outs = [block(x) for block in self._children]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: basic_layers.py HybridConcurrent:60)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children]
        return F.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block, e.g. the skip branch of a HybridConcurrent
    (ref: basic_layers.py Identity:93)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x
