"""Gluon contrib: experimental blocks
(ref: python/mxnet/gluon/contrib/__init__.py).
"""
from . import nn
from . import rnn
