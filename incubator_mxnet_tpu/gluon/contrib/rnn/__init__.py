"""Contrib recurrent cells
(ref: python/mxnet/gluon/contrib/rnn/__init__.py).
"""
from .rnn_cell import *
from . import rnn_cell
