"""Gluon Parameter / ParameterDict.

TPU-native rebirth of python/mxnet/gluon/parameter.py (775 LoC): same public
surface — deferred shape init, per-context replicas, ``grad_req``,
save/load — but device replication is logical: one device buffer per
Context, with the sharded/pjit path (parallel package) treating a Parameter
as a named leaf in the train-state pytree.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..ndarray import NDArray
from .. import ndarray as _nd
from .. import initializer
from .. import autograd

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization (ref: parameter.py:36)."""


class Parameter(object):
    """A trainable parameter (ref: gluon/parameter.py class Parameter).

    Holds one NDArray per context.  ``shape`` entries of 0 are inferred on
    first forward (deferred init), matching the reference contract.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default",
                 sharding=None):
        self._var = None
        self._data = None   # OrderedDict[Context, NDArray]
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        # per-dimension mesh axis names, e.g. ("tp", None): the GSPMD
        # rebirth of ctx_group model parallelism (SURVEY §2.4 — placement
        # is a sharding annotation, the compiler inserts the collectives)
        self.sharding = tuple(sharding) if sharding is not None else None
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req
        self.init = init
        self._stype = stype
        self._grad_stype = grad_stype

    def __repr__(self):
        s = "Parameter {name} (shape={shape}, dtype={dtype})"
        return s.format(name=self.name, shape=self.shape, dtype=self.dtype)

    # -- grad_req ----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), \
            "grad_req must be one of 'write', 'add', or 'null', but got '%s'" % req
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null" and self._grad is not None:
            self._grad = None
            for d in self._check_and_get(self._data, list):
                d._grad = None
                d._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    # -- helpers -----------------------------------------------------------
    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is not None:
            if ctx is list:
                return list(arr_dict.values())
            if ctx is None:
                if len(arr_dict) == 1:
                    return list(arr_dict.values())[0]
                ctx = current_context()
            if isinstance(ctx, Context):
                if ctx in arr_dict:
                    return arr_dict[ctx]
                # device_typeid fallback: tpu() matches tpu(0)
                for c, v in arr_dict.items():
                    if c.device_type == ctx.device_type:
                        return v
            raise RuntimeError(
                "Parameter %s was not initialized on context %s. "
                "It was only initialized on %s." % (
                    self.name, str(ctx), str(self._ctx_list)))
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter %s has not been initialized yet because initialization "
                "was deferred. Actual initialization happens during the first "
                "forward pass. Please pass one batch of data through the network "
                "before accessing Parameters." % self.name)
        raise RuntimeError(
            "Parameter %s has not been initialized. Note that you should initialize "
            "parameters and create Trainer with Block.collect_params() instead of "
            "Block.params because the later does not include Parameters of "
            "nested child Blocks" % self.name)

    def _load_init(self, data, ctx):
        """Re-init from loaded data (ref: parameter.py _load_init)."""
        if self.shape:
            for self_dim, data_dim in zip(self.shape, data.shape):
                assert self_dim in (0, data_dim), \
                    "Failed loading Parameter '%s' from saved params: " \
                    "shape incompatible expected %s vs saved %s" % (
                        self.name, str(self.shape), str(data.shape))
            self.shape = tuple(i if i != 0 else j
                               for i, j in zip(self.shape, data.shape))
        if self.dtype:
            assert np.dtype(self.dtype).type == data.dtype.type, \
                "Failed loading Parameter '%s' from saved params: " \
                "dtype incompatible expected %s vs saved %s" % (
                    self.name, str(self.dtype), str(data.dtype))
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is None:
            if self._deferred_init:
                assert ctx is None or set(ctx) == set(self._deferred_init[1]), \
                    "Failed to load Parameter '%s' on %s because it was " \
                    "previous initialized on %s." % (
                        self.name, str(ctx), str(self.list_ctx()))
                ctx = self._deferred_init[1]
            elif ctx is None:
                ctx = [cpu()]
            self._init_impl(data, ctx)
        else:
            assert ctx is None or set(ctx) == set(self.list_ctx()), \
                "Failed to load Parameter '%s' on %s because it was " \
                "previous initialized on %s." % (
                    self.name, str(ctx), str(self.list_ctx()))
            self.set_data(data)
        self._deferred_init = ()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if isinstance(init, str):
            init = initializer.create(init)
        assert self.shape is not None and np.prod(self.shape) > 0, \
            "Cannot initialize Parameter '%s' because it has invalid shape: %s. " \
            "Please specify in_units, in_channels, etc for `Block`s." % (
                self.name, str(self.shape))
        with autograd.pause():
            if data is None:
                data = _nd.empty(self.shape, dtype=self.dtype, ctx=cpu())
                # the __init__ attr routes straight to the param's own
                # initializer; otherwise default_init's suffix dispatch runs
                # (ref: parameter.py _finish_deferred_init → InitDesc attrs)
                attrs = {"__init__": init.dumps()} \
                    if isinstance(init, initializer.Initializer) else {}
                initializer.create(default_init)(
                    initializer.InitDesc(self.name, attrs), data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx_list = list(ctx_list)
        self._data = OrderedDict()
        for ctx in self._ctx_list:
            self._data[ctx] = data.copyto(ctx) if isinstance(data, NDArray) \
                else _nd.array(data, ctx=ctx, dtype=self.dtype)
        self._init_grad()

    def _init_grad(self):
        if self.grad_req == "null":
            self._grad = None
            return
        self._grad = OrderedDict()
        for ctx, d in self._data.items():
            g = _nd.array(np.zeros(d.shape, np.dtype(self.dtype)), ctx=ctx)
            self._grad[ctx] = g
            d._grad = g
            d._grad_req = self.grad_req
            autograd.mark_variables([d], [g], self.grad_req)

    def _reduce(self):
        """Average over contexts (ref: parameter.py _reduce)."""
        data = self.list_data()
        if len(data) == 1:
            return data[0].copyto(cpu())
        acc = data[0].asnumpy().astype(np.float64)
        for d in data[1:]:
            acc = acc + d.asnumpy()
        return _nd.array((acc / len(data)).astype(self.dtype), ctx=cpu())

    # -- public API --------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """ref: gluon/parameter.py Parameter.initialize."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if self.shape is None or np.prod(self.shape) <= 0:
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError("Cannot initialize Parameter '%s' because it has "
                             "invalid shape: %s." % (self.name, str(self.shape)))
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def reset_ctx(self, ctx):
        """Move to new contexts (ref: parameter.py reset_ctx)."""
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = self._reduce()
            with autograd.pause():
                self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError("Cannot reset context for Parameter '%s' because it "
                             "has not been initialized." % self.name)

    def set_data(self, data):
        """ref: parameter.py set_data."""
        self.shape = tuple(data.shape)
        if self._data is None:
            assert self._deferred_init, \
                "Parameter '%s' has not been initialized" % self.name
            self._deferred_init = self._deferred_init[:3] + (data,)
            return
        for arr in self._data.values():
            arr._write(jnp.asarray(
                data.asnumpy() if isinstance(data, NDArray) else data,
                arr._read().dtype))

    def data(self, ctx=None):
        """Returns this parameter on one context (ref: parameter.py data)."""
        return self._check_and_get(self._data, ctx)

    def list_data(self):
        return self._check_and_get(self._data, list)

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' "
                "because grad_req='null'" % self.name)
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' "
                "because grad_req='null'" % self.name)
        return self._check_and_get(self._grad, list)

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError("Parameter '%s' has not been initialized" % self.name)
        return self._ctx_list

    def zero_grad(self):
        """ref: parameter.py zero_grad."""
        if self._grad is None:
            return
        for g in self._grad.values():
            g._write(jnp.zeros(g.shape, g._read().dtype))

    def var(self):
        """Symbol view of this parameter (ref: parameter.py var)."""
        if self._var is None:
            from ..symbol import var as _sym_var
            self._var = _sym_var(self.name, shape=self.shape, dtype=self.dtype,
                                 lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                                 init=self.init)
        return self._var

    def cast(self, dtype):
        """ref: parameter.py cast."""
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = OrderedDict(
                (ctx, d.astype(dtype)) for ctx, d in self._data.items())
            self._init_grad()


class Constant(Parameter):
    """Non-trainable constant (ref: gluon/parameter.py class Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd.array(value)
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr._write(value._read())
        # registry key must equal __name__.lower() so dumps() round-trips
        Init.__name__ = "Constant_" + name
        initializer._INIT_REGISTRY[Init.__name__.lower()] = Init

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=Init())


def _attr_equal(a, b):
    """Attribute equivalence for Parameter reconciliation: initializer
    instances compare by configuration (dumps), not identity."""
    if a == b:
        return True
    if isinstance(a, initializer.Initializer) and \
            isinstance(b, initializer.Initializer):
        return a.dumps() == b.dumps()
    return False


class ParameterDict(object):
    """Prefix-scoped dict of Parameters (ref: gluon/parameter.py:560)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __repr__(self):
        s = "{name}(\n{content}\n)"
        name = self._prefix + " " if self._prefix else ""
        return s.format(name=name, content="\n".join(
            "  " + repr(v) for v in self.values()))

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._shared._params[name]
        return None

    def get(self, name, **kwargs):
        """Get-or-create with attribute reconciliation (ref: parameter.py get)."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and len(v) == len(existing):
                        inferred_shape = []
                        matched = True
                        for dim1, dim2 in zip(v, existing):
                            if dim1 != dim2 and dim1 * dim2 != 0:
                                matched = False
                                break
                            elif dim1 == dim2:
                                inferred_shape.append(dim1)
                            elif dim1 == 0:
                                inferred_shape.append(dim2)
                            else:
                                inferred_shape.append(dim1)
                        if matched:
                            param.shape = tuple(inferred_shape)
                            continue
                    assert v is None or _attr_equal(v, existing), \
                        "Cannot retrieve Parameter '%s' because desired attribute " \
                        "does not match with stored for attribute '%s': " \
                        "desired '%s' vs stored '%s'." % (
                            name, k, str(v), str(getattr(param, k)))
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        """ref: parameter.py get_constant."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("No constant named '{}'. Please specify value "
                               "if you want to create a new constant.".format(name))
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            assert isinstance(param, Constant), \
                "Parameter '{}' already exists but it is not a constant.".format(name)
        return param

    def update(self, other):
        """ref: parameter.py ParameterDict.update."""
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    "Cannot update self with other because they have different " \
                    "Parameters with the same name '%s'" % k
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        """ref: parameter.py ParameterDict.initialize."""
        if init is None:
            init = initializer.Uniform()
        if verbose:
            init.set_verbosity(verbose=verbose)
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        """ref: parameter.py ParameterDict.save → NDArray save format."""
        from ..ndarray import save as nd_save
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    "Prefix '%s' is to be striped before saving, but Parameter's "
                    "name '%s' does not start with '%s'." % (
                        strip_prefix, param.name, strip_prefix))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        """ref: parameter.py ParameterDict.load."""
        from ..ndarray import load as nd_load
        if restore_prefix:
            for name in self.keys():
                assert name.startswith(restore_prefix), \
                    "restore_prefix is '%s' but Parameters name '%s' does not start " \
                    "with '%s'" % (restore_prefix, name, restore_prefix)
        lprefix = len(restore_prefix)
        loaded = nd_load(filename)
        arg_dict = {restore_prefix + k.split(":", 1)[-1]: v
                    for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    "Parameter '%s' is missing in file '%s'" % (name[lprefix:], filename)
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    "Parameter '%s' loaded from file '%s' is not present in " \
                    "ParameterDict" % (name[lprefix:], filename)
                continue
            self[name]._load_init(arg_dict[name], ctx)
