"""Model zoo (ref: python/mxnet/gluon/model_zoo/__init__.py)."""
from . import model_store
from . import vision
from .vision import get_model
