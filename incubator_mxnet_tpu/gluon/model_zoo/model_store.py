"""Pretrained model store (ref: python/mxnet/gluon/model_zoo/model_store.py).

This environment has no network egress: pretrained weights resolve only from
the local root (default ``$MXTPU_HOME/models``, i.e. ~/.mxnet/models). The
API shape (get_model_file, purge) matches the reference; MXTPU_GLUON_REPO /
MXNET_GLUON_REPO is honored for the download URL it would have used.
"""
from __future__ import annotations

import os

from ... import config as _config

__all__ = ["get_model_file", "purge"]


def _default_root():
    return os.path.join(_config.data_home(), "models")


def get_model_file(name, root=None):
    """Locate a pretrained parameter file locally (ref: model_store.py
    get_model_file; download path requires egress, absent here)."""
    root = os.path.expanduser(root or _default_root())
    file_path = os.path.join(root, name + ".params")
    if os.path.exists(file_path):
        return file_path
    repo = _config.get("GLUON_REPO")
    raise IOError(
        "Pretrained model file %s is not present and this environment has no "
        "network egress (would fetch from %s). Place the .params file there "
        "manually." % (file_path, repo))


def purge(root=None):
    """ref: model_store.py purge."""
    root = os.path.expanduser(root or _default_root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
