"""Pretrained model store (ref: python/mxnet/gluon/model_zoo/model_store.py).

This environment has no network egress: pretrained weights resolve only from
the local root (default ~/.mxnet/models). The API shape (get_model_file,
purge) matches the reference.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Locate a pretrained parameter file locally (ref: model_store.py
    get_model_file; download path requires egress, absent here)."""
    root = os.path.expanduser(root or os.path.join("~", ".mxnet", "models"))
    file_path = os.path.join(root, name + ".params")
    if os.path.exists(file_path):
        return file_path
    raise IOError(
        "Pretrained model file %s is not present and this environment has no "
        "network egress. Place the .params file there manually." % file_path)


def purge(root=os.path.join("~", ".mxnet", "models")):
    """ref: model_store.py purge."""
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
